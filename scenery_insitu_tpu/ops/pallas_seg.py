"""Pallas TPU kernel for the segmented-scan write fold (ops/seg_fold.py).

Same algorithm as the XLA schedule — parallel start flags, segment ids by
running count, segmented transmittance, K masked reductions — with the
memory movement pinned down: the sample chunk, the K-slot state and the
per-slice ``(slot, v)`` records all live in VMEM pixel strips, and the
``[K,...]`` state crosses HBM once per chunk via ``input_output_aliases``.

Contrast with the round-3 two-phase kernel (ops/pallas_march.py), which
kept the *sequential* ``ss.push`` machine and deferred 7×C close-event
values across the whole unrolled slice loop as SSA live ranges — the
hardware-measured suspect for its 300×-above-floor cost. Here phase A
carries just four small values per pixel between slices (running start
count, running transmittance, prev rgb, prev empty) and writes each
slice's ``(slot, premultiplied-scaled rgba)`` record straight to a VMEM
scratch ref, so no live range spans the loop; phase B re-reads the
scratch per slot row — VMEM-to-register traffic, not HBM.

Semantics are identical to ``seg_fold.seg_fold_chunk`` (tests pin
interpret-mode equality) and therefore to C sequential ``ss.push`` calls
up to fp association (≅ the reference's fused single-kernel generation,
VDIGenerator.comp:380-529 + AccumulateVDI.comp:69-98).

State layout (3 aliased arrays, same convention as pallas_march):
``color f32[K,4,H,W]``, ``depth f32[K,2,H,W]`` (start/end; start init
+inf, end init -inf), ``small f32[5,H,W]`` = cnt[0] (f32-encoded),
prev_rgb[1:4], prev_empty[4]. Helpers convert to/from
``seg_fold.SegFoldState`` so the march code handles ONE state type.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from scenery_insitu_tpu.ops import seg_fold as sf
from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.pallas_march import _pick_block_w
from scenery_insitu_tpu.ops.pallas_util import TILE_H, should_interpret
from scenery_insitu_tpu.utils.compat import tpu_compiler_params

_CNT, _PREV_RGB, _PREV_EMPTY = 0, slice(1, 4), 4
_NSMALL = 5
# estimate floor on K so the chosen block width (and thus the exact kernel
# Mosaic compiles) is identical for every K <= _EST_K and matches the
# compile probe's geometry. The floor actually applied lives in
# pallas_march (strip_fpp uses it); alias it so the two can never diverge.
from scenery_insitu_tpu.ops.pallas_march import _EST_K, strip_fpp  # noqa: F401


def init_seg_packed(k: int, height: int, width: int):
    """Packed fold state ≅ seg_fold.init_seg_state — built directly in
    packed layout so a march can carry the triple through its scan with
    no per-chunk stack/concat traffic (the depth plane alone is
    [K,2,H,W]; re-materializing it every chunk would cost more HBM than
    the kernel's own state pass)."""
    color = jnp.zeros((k, 4, height, width), jnp.float32)
    depth = jnp.stack([
        jnp.full((k, height, width), jnp.inf, jnp.float32),
        jnp.full((k, height, width), -jnp.inf, jnp.float32)], axis=1)
    small = jnp.zeros((_NSMALL, height, width), jnp.float32)
    small = small.at[_PREV_EMPTY].set(1.0)
    return (color, depth, small)


def pack_seg_state(st: sf.SegFoldState):
    small = jnp.concatenate([
        st.cnt.astype(jnp.float32)[None],
        st.prev_rgb,
        st.prev_empty.astype(jnp.float32)[None]])
    return (st.out_color,
            jnp.stack([st.out_start, st.out_end], axis=1),
            small)


def unpack_seg_state(packed) -> sf.SegFoldState:
    color, depth, small = packed
    return sf.SegFoldState(
        out_color=color, out_start=depth[:, 0], out_end=depth[:, 1],
        cnt=small[_CNT].astype(jnp.int32),
        prev_rgb=small[_PREV_RGB],
        prev_empty=small[_PREV_EMPTY] > 0.5)


def _phase_b(ev_slot, ev_rgba, t0_of, t1_of, ci_, di_, co, do_,
             max_k: int):
    """Rolled K-loop merge shared by the seg and fused kernels: per slot
    row, masked-sum the per-slice records and under-merge into the
    aliased [K,...] state (touched once per chunk). ``t0_of(m)``/
    ``t1_of(m)`` produce the masked depth candidates for a slot mask so
    each kernel can source depths from its own layout."""
    def slot_body(kk, _):
        m = ev_slot == kk.astype(jnp.float32)
        mf = m.astype(jnp.float32)
        contrib = jnp.sum(ev_rgba * mf[:, None], axis=0)
        d0 = jnp.min(t0_of(m), axis=0)
        d1 = jnp.max(t1_of(m), axis=0)
        oc = ci_[pl.dslice(kk, 1)]
        co[pl.dslice(kk, 1)] = oc + (1.0 - oc[:, 3:4]) * contrib[None]
        dr = di_[pl.dslice(kk, 1)]
        do_[pl.dslice(kk, 1)] = jnp.stack(
            [jnp.minimum(dr[0, 0], d0), jnp.maximum(dr[0, 1], d1)])[None]
        return 0

    jax.lax.fori_loop(0, max_k, slot_body, 0)


def _phase_a(rgba_ref, thr, smi_, smo, ev_ref, kf):
    """Per-slice (slot, v) records from the shaded rgba stream; 4 small
    live carries. Shared by the plane-depth and compact-depth kernels."""
    nc = rgba_ref.shape[0]
    sm = smi_[...]
    run_cnt = sm[_CNT]
    pr = sm[_PREV_RGB]
    pe = sm[_PREV_EMPTY] > 0.5

    t_run = jnp.ones_like(thr)
    for s in range(nc):
        rgba = rgba_ref[s]
        emp = rgba[3] < ss.EMPTY_ALPHA
        d = rgba[:3] - pr
        diff = jnp.sqrt(jnp.sum(d * d, axis=0))
        start = ~emp & (pe | (diff > thr))
        run_cnt = run_cnt + start.astype(jnp.float32)
        sid = run_cnt - 1.0
        reset = start & (sid <= kf)
        t_here = jnp.where(reset, 1.0, t_run)
        t_run = t_here * (1.0 - jnp.where(emp, 0.0, rgba[3]))
        slotf = jnp.where(emp, -1.0, jnp.minimum(sid, kf))
        v = rgba * (t_here * (~emp).astype(jnp.float32))[None]
        ev_ref[s] = jnp.concatenate([slotf[None], v])
        pr = jnp.where(emp[None], pr, rgba[:3])
        pe = emp

    smo[...] = jnp.concatenate([
        run_cnt[None], pr, pe.astype(jnp.float32)[None]])


def _seg_kernel(rgba_ref, td_ref, thr_ref, ci_, di_, smi_,
                co, do_, smo, ev_ref, *, max_k: int):
    thr = thr_ref[...]
    _phase_a(rgba_ref, thr, smi_, smo, ev_ref, jnp.float32(max_k - 1))

    # ---- phase B: rolled K loop, state touched once per chunk
    ev = ev_ref[...]                                       # [C, 5, TH, WB]
    _phase_b(ev[:, 0], ev[:, 1:5],
             lambda m: jnp.where(m, td_ref[:, 0], jnp.inf),
             lambda m: jnp.where(m, td_ref[:, 1], -jnp.inf),
             ci_, di_, co, do_, max_k)


def _seg_kernel_compact(rgba_ref, len_ref, thr_ref, sk0_ref, sk1_ref,
                        ci_, di_, smi_, co, do_, smo, ev_ref, *,
                        max_k: int):
    """_seg_kernel with the depth planes computed IN-KERNEL from the
    per-slice ratios and the per-pixel ray length (t = sk * length —
    exactly what the march's outer product materialized): the [C,2,H,W]
    td stream never exists in HBM, the march's biggest remaining stream
    term after rgba (~3.4 GB/march at the 512³ flagship)."""
    thr = thr_ref[...]
    _phase_a(rgba_ref, thr, smi_, smo, ev_ref, jnp.float32(max_k - 1))

    ev = ev_ref[...]                                       # [C, 5, TH, WB]
    ln = len_ref[...]                                      # [TH, WB]
    t0a = sk0_ref[...] * ln[None]                          # [C, TH, WB]
    t1a = sk1_ref[...] * ln[None]
    _phase_b(ev[:, 0], ev[:, 1:5],
             lambda m: jnp.where(m, t0a, jnp.inf),
             lambda m: jnp.where(m, t1a, -jnp.inf),
             ci_, di_, co, do_, max_k)


def fold_chunk_packed(packed, rgba: jnp.ndarray, t0=None, t1=None,
                      threshold: jnp.ndarray = None, *, max_k: int,
                      interpret: Optional[bool] = None,
                      sk0=None, sk1=None, length=None):
    """Fold one chunk on VMEM pixel strips, packed-state in/out.

    ``packed`` is the `init_seg_packed` triple; carrying it through the
    march's scan keeps the [K,...] state layout stable across chunks so
    ``input_output_aliases`` updates it in place — no per-chunk
    stack/slice re-materialization. Semantics = seg_fold.seg_fold_chunk.

    Depth comes in one of two forms:
    - ``t0``/``t1`` f32[C,H,W] planes (tests / arbitrary streams), or
    - COMPACT: ``sk0``/``sk1`` f32[C] per-slice ratios + ``length``
      f32[H,W] — the kernel computes t = sk*length itself, so the
      [C,2,H,W] depth stream never exists in HBM (the production march
      path; its t0/t1 are exactly this outer product).
    """
    if interpret is None:
        interpret = should_interpret()
    planes_any = t0 is not None or t1 is not None
    compact_any = (sk0 is not None or sk1 is not None
                   or length is not None)
    planes_full = t0 is not None and t1 is not None
    compact_full = (sk0 is not None and sk1 is not None
                    and length is not None)
    if planes_any and compact_any:
        raise ValueError("depth forms cannot be mixed: got t0/t1 plane "
                         "args together with sk0/sk1/length compact args")
    if not (planes_full or compact_full):
        raise ValueError("pass exactly one COMPLETE depth form: "
                         "(t0, t1) or (sk0, sk1, length)")
    compact = compact_full
    color, depth, small = packed
    kk = color.shape[0]
    _, _, h, w = color.shape
    c = rgba.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))

    # compact: the rgba stream shrinks 6C->4C and gains 1 length plane,
    # but the kernel broadcasts its own t0a/t1a [C,TH,WB] temporaries —
    # counted in per_slice_records exactly as _fused_fpp documents
    fpp = strip_fpp(c, kk, small_rows=_NSMALL, count_plane=False,
                    per_slice_records=7 if compact else 5,
                    stream_per_slice=4 if compact else 6,
                    extra_planes=1 if compact else 0)
    wb = _pick_block_w(w, 4 * TILE_H * fpp)
    grid = (h // TILE_H, pl.cdiv(w, wb))
    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    state_specs = [row(kk, 4), row(kk, 2), row(_NSMALL)]
    if compact:
        length = jnp.broadcast_to(jnp.asarray(length, jnp.float32), (h, w))
        sk0 = jnp.asarray(sk0, jnp.float32).reshape(c, 1, 1)
        sk1 = jnp.asarray(sk1, jnp.float32).reshape(c, 1, 1)
        sk_spec = pl.BlockSpec((c, 1, 1), lambda j, i: (0, 0, 0))
        out = pl.pallas_call(
            functools.partial(_seg_kernel_compact, max_k=max_k),
            grid=grid,
            in_specs=[row(c, 4), row(), row(), sk_spec, sk_spec]
            + state_specs,
            out_specs=state_specs,
            out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype)
                       for p in packed],
            scratch_shapes=[pltpu.VMEM((c, 5, TILE_H, wb), jnp.float32)],
            input_output_aliases={5: 0, 6: 1, 7: 2},
            interpret=interpret,
        )(rgba, length, threshold, sk0, sk1, *packed)
        return tuple(out)

    td = jnp.stack([t0, t1], axis=1)                       # [C, 2, H, W]
    out = pl.pallas_call(
        functools.partial(_seg_kernel, max_k=max_k),
        grid=grid,
        in_specs=[row(c, 4), row(c, 2), row()] + state_specs,
        out_specs=state_specs,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype) for p in packed],
        scratch_shapes=[pltpu.VMEM((c, 5, TILE_H, wb), jnp.float32)],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=interpret,
    )(rgba, td, threshold, *packed)
    return tuple(out)


def seg_fold_chunk(st: sf.SegFoldState, rgba: jnp.ndarray, t0: jnp.ndarray,
                   t1: jnp.ndarray, threshold: jnp.ndarray, *, max_k: int,
                   interpret: Optional[bool] = None) -> sf.SegFoldState:
    """Drop-in twin of ``seg_fold.seg_fold_chunk`` (NamedTuple in/out).
    Convenience for tests/small streams — production marches carry the
    packed triple via `init_seg_packed` + `fold_chunk_packed` instead,
    avoiding the pack/unpack copies this wrapper pays per call."""
    packed = pack_seg_state(st)
    out = fold_chunk_packed(packed, rgba, t0, t1, threshold, max_k=max_k,
                            interpret=interpret)
    return unpack_seg_state(out)


# ----------------------------------------------- fused shade+fold kernel


def _tf_consts(tf) -> tuple:
    """The transfer function's knots as PYTHON floats, baked into the
    kernel as compile-time constants (zero-slope padded knots are skipped
    at kernel-build time — free TF trimming). Raises if the TF is traced:
    every production path closes over a concrete TF (the session rebuilds
    its compiled steps on a runtime TF swap), and a traced TF would need
    the knots as kernel operands — use fold="pallas_seg" there."""
    # only the tracer-leak family is "the TF is traced"; anything else
    # (renamed field, numpy failure) is a genuine bug and must propagate
    try:
        ax = np.asarray(tf.alpha_x).tolist()
        am = np.asarray(tf.alpha_m).tolist()
        ab = float(np.asarray(tf.alpha_b))
        cx = np.asarray(tf.color_x).tolist()
        cm = np.asarray(tf.color_m).tolist()
        cb = np.asarray(tf.color_b).tolist()
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError) as e:
        raise ValueError(
            "the fused fold schedules (pallas_fused / fused_stream) bake "
            "the transfer function into the kernel and need a CONCRETE "
            f"TransferFunction (got traced values: {e}); pass the TF as "
            "a closure constant or use fold='pallas_seg'") from None
    return (tuple(ax), tuple(am), ab, tuple(cx),
            tuple(tuple(r) for r in cm), tuple(cb))


def _fused_kernel(val_ref, len_ref, ratio_ref, thr_ref, sk0_ref, sk1_ref,
                  ci_, di_, smi_, co, do_, smo, ev_ref, *,
                  max_k: int, tfc: tuple):
    """Shade (TF + opacity correction) + segmented fold in ONE kernel —
    the TPU counterpart of the reference's fused generation kernel
    (VDIGenerator.comp:380-529 shades and accumulates per ray without
    leaving registers). Input is the 1-channel resampled value plane
    (sentinel -1 marks outside-volume/dead samples) instead of the
    4-channel post-TF rgba stream: 4x less HBM into the kernel, and the
    TF's relu-sum runs on VMEM-resident data with its knots baked in as
    immediates (`_tf_consts`)."""
    ax, am, ab, cx, cm, cb = tfc
    nc = val_ref.shape[0]
    thr = thr_ref[...]
    length = len_ref[...]
    ratio = ratio_ref[...]
    t0_all = sk0_ref[...] * length[None]                   # [C, TH, WB]
    t1_all = sk1_ref[...] * length[None]

    sm = smi_[...]
    run_cnt = sm[_CNT]
    pr = sm[_PREV_RGB]
    pe = sm[_PREV_EMPTY] > 0.5
    kf = jnp.float32(max_k - 1)

    t_run = jnp.ones_like(thr)
    for s in range(nc):
        v_raw = val_ref[s]
        outside = v_raw < -0.5
        x = jnp.clip(v_raw, 0.0, 1.0)
        # knot-form TF with baked immediates; zero-slope (padding) knots
        # compile to nothing
        a = ab
        for xi, mi in zip(ax, am):
            if mi != 0.0:
                a = a + mi * jnp.maximum(x - xi, 0.0)
        chans = []
        for ch in range(3):
            cch = cb[ch]
            for xi, row in zip(cx, cm):
                if row[ch] != 0.0:
                    cch = cch + row[ch] * jnp.maximum(x - xi, 0.0)
            chans.append(cch)
        a = jnp.where(outside, 0.0, a)
        # adjust_opacity(a, ratio), formula-exact
        a = 1.0 - jnp.power(jnp.clip(1.0 - a, 1e-7, 1.0), ratio)

        emp = a < ss.EMPTY_ALPHA
        r3 = jnp.stack([c * a for c in chans])             # premult [3,..]
        d = r3 - pr
        diff = jnp.sqrt(jnp.sum(d * d, axis=0))
        start = ~emp & (pe | (diff > thr))
        run_cnt = run_cnt + start.astype(jnp.float32)
        sid = run_cnt - 1.0
        reset = start & (sid <= kf)
        t_here = jnp.where(reset, 1.0, t_run)
        t_run = t_here * (1.0 - jnp.where(emp, 0.0, a))
        slotf = jnp.where(emp, -1.0, jnp.minimum(sid, kf))
        live = t_here * (~emp).astype(jnp.float32)
        ev_ref[s] = jnp.concatenate([
            slotf[None], r3 * live[None], (a * live)[None],
            t0_all[s][None], t1_all[s][None]])
        pr = jnp.where(emp[None], pr, r3)
        pe = emp

    smo[...] = jnp.concatenate([
        run_cnt[None], pr, pe.astype(jnp.float32)[None]])

    ev = ev_ref[...]                                       # [C, 7, TH, WB]
    _phase_b(ev[:, 0], ev[:, 1:5],
             lambda m: jnp.where(m, ev[:, 5], jnp.inf),
             lambda m: jnp.where(m, ev[:, 6], -jnp.inf),
             ci_, di_, co, do_, max_k)


def _fused_fpp(c: int, k: int) -> int:
    """Fused-kernel strip budget via the shared formula: 1-channel value
    stream (vs 6C rgba+depth), 2 extra per-pixel planes (length, ratio),
    and 9 per-slice record floats (7 scratch + the t0/t1 temporaries the
    kernel broadcasts itself)."""
    from scenery_insitu_tpu.ops.pallas_march import strip_fpp

    return strip_fpp(c, k, small_rows=_NSMALL, count_plane=False,
                     per_slice_records=9, stream_per_slice=1,
                     extra_planes=2)


def fused_fold_chunk(packed, val: jnp.ndarray, length: jnp.ndarray,
                     ratio: jnp.ndarray, sk0: jnp.ndarray,
                     sk1: jnp.ndarray, threshold: jnp.ndarray, *,
                     max_k: int, tf, interpret: Optional[bool] = None):
    """Fold one chunk straight from the resampled VALUE plane.

    val f32[C,H,W] with -1 sentinel for dead samples; length/ratio/
    threshold f32[H,W]; sk0/sk1 f32[C] per-slice depth ratios (t0/t1 =
    sk*length computed in-kernel — two full [C,H,W] depth streams never
    exist). ``tf`` must be a concrete TransferFunction (baked in)."""
    if interpret is None:
        interpret = should_interpret()
    tfc = _tf_consts(tf)
    color, depth, small = packed
    kk = color.shape[0]
    _, _, h, w = color.shape
    c = val.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    length = jnp.broadcast_to(jnp.asarray(length, jnp.float32), (h, w))
    ratio = jnp.broadcast_to(jnp.asarray(ratio, jnp.float32), (h, w))
    sk0 = jnp.asarray(sk0, jnp.float32).reshape(c, 1, 1)
    sk1 = jnp.asarray(sk1, jnp.float32).reshape(c, 1, 1)

    wb = _pick_block_w(w, 4 * TILE_H * _fused_fpp(c, kk))
    grid = (h // TILE_H, pl.cdiv(w, wb))
    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    state_specs = [row(kk, 4), row(kk, 2), row(_NSMALL)]
    sk_spec = pl.BlockSpec((c, 1, 1), lambda j, i: (0, 0, 0))
    out = pl.pallas_call(
        functools.partial(_fused_kernel, max_k=max_k, tfc=tfc),
        grid=grid,
        in_specs=[row(c), row(), row(), row(), sk_spec, sk_spec]
        + state_specs,
        out_specs=state_specs,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype) for p in packed],
        scratch_shapes=[pltpu.VMEM((c, 7, TILE_H, wb), jnp.float32)],
        input_output_aliases={6: 0, 7: 1, 8: 2},
        interpret=interpret,
    )(val, length, ratio, threshold, sk0, sk1, *packed)
    return tuple(out)


# ------------------------------------------- whole-march stream-fold kernel


def _fused_stream_kernel(val_ref, len_ref, ratio_ref, thr_ref, sk0_ref,
                         sk1_ref, ci_, di_, smi_, co, do_, smo, ev_ref, *,
                         max_k: int, tfc: tuple):
    """The fused shade+fold kernel over a WHOLE-march grid: the chunk
    loop is the innermost grid dimension and every state block's index
    map ignores it, so Mosaic keeps the [K,...] state resident in VMEM
    across all chunks of a pixel strip and writes it back ONCE — the
    state's HBM traffic drops from (2 x per chunk) to (1 x per march),
    the last memory term the per-chunk kernels still paid. The val
    stream must pre-exist in HBM (f32[S,H,W], built by the march's
    matmul phase), which the 1-channel fused feed makes affordable.
    Phase logic is identical to `_fused_kernel`; cross-chunk
    continuation works exactly as between per-chunk calls because phase
    B merges into the (now VMEM-resident) state after every chunk.

    Accumulation reads/writes the OUTPUT refs (initialized from the
    aliased inputs at the strip's first chunk): a revisited block only
    persists on the output side — re-reading the input refs after
    chunk 0 would see the strip's INITIAL state, not the accumulated
    one (the standard Pallas grid-accumulator pattern)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        co[...] = ci_[...]
        do_[...] = di_[...]
        smo[...] = smi_[...]

    _fused_kernel(val_ref, len_ref, ratio_ref, thr_ref, sk0_ref, sk1_ref,
                  co, do_, smo, co, do_, smo, ev_ref,
                  max_k=max_k, tfc=tfc)


def fused_stream_fold(packed, val: jnp.ndarray, length: jnp.ndarray,
                      ratio: jnp.ndarray, sk0: jnp.ndarray,
                      sk1: jnp.ndarray, threshold: jnp.ndarray, *,
                      max_k: int, chunk: int, tf,
                      interpret: Optional[bool] = None):
    """Fold an ENTIRE pre-materialized value stream in one pallas_call.

    val f32[S,H,W] (S a multiple of ``chunk``; -1 sentinel for dead
    samples); sk0/sk1 f32[S] per-slice depth ratios; length/ratio/
    threshold f32[H,W]. ``packed`` = `init_seg_packed` triple. The fold
    state crosses HBM once per strip instead of once per chunk."""
    if interpret is None:
        interpret = should_interpret()
    tfc = _tf_consts(tf)
    color, depth, small = packed
    kk = color.shape[0]
    _, _, h, w = color.shape
    s_total = val.shape[0]
    c = chunk
    if s_total % c:
        raise ValueError(f"stream length {s_total} not a multiple of "
                         f"chunk {c}")
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    length = jnp.broadcast_to(jnp.asarray(length, jnp.float32), (h, w))
    ratio = jnp.broadcast_to(jnp.asarray(ratio, jnp.float32), (h, w))
    sk0 = jnp.asarray(sk0, jnp.float32).reshape(s_total, 1, 1)
    sk1 = jnp.asarray(sk1, jnp.float32).reshape(s_total, 1, 1)

    wb = _pick_block_w(w, 4 * TILE_H * _fused_fpp(c, kk))
    nchunks = s_total // c
    # chunk dim INNERMOST (fastest): for each strip, all chunks run
    # consecutively and the constant-index state blocks are revisited
    grid = (h // TILE_H, pl.cdiv(w, wb), nchunks)
    row = lambda *lead: pl.BlockSpec(
        lead + (TILE_H, wb), lambda j, i, ci: (0,) * len(lead) + (j, i))
    stream = pl.BlockSpec((c, TILE_H, wb), lambda j, i, ci: (ci, j, i))
    sk_spec = pl.BlockSpec((c, 1, 1), lambda j, i, ci: (ci, 0, 0))
    state_specs = [row(kk, 4), row(kk, 2), row(_NSMALL)]
    out = pl.pallas_call(
        functools.partial(_fused_stream_kernel, max_k=max_k, tfc=tfc),
        grid=grid,
        in_specs=[stream, row(), row(), row(), sk_spec, sk_spec]
        + state_specs,
        out_specs=state_specs,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype) for p in packed],
        scratch_shapes=[pltpu.VMEM((c, 7, TILE_H, wb), jnp.float32)],
        input_output_aliases={6: 0, 7: 1, 8: 2},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(val, length, ratio, threshold, sk0, sk1, *packed)
    return tuple(out)


# ------------------------------------------------------------ compile probe

_PROBE: dict = {}


def seg_compile_ok(max_k: int = 32, chunk: int = 16,
                   width: int = 2048) -> bool:
    """One-time Mosaic-acceptance probe at the REAL (K, chunk, width) so
    `slicer.make_spec`'s "auto" can fall back to the XLA seg fold instead
    of failing inside a traced frame step. Cached per (backend, shape)."""
    key = (jax.default_backend(), int(max_k), int(chunk), int(width))
    ok = _PROBE.get(key)
    if ok is None:
        try:
            k, c, h, w = int(max_k), int(chunk), TILE_H, int(width)
            sds = jax.ShapeDtypeStruct

            # probe BOTH kernel variants the production march can trace:
            # the compact-depth form (what the march feeds) and the
            # td-plane form (tests / arbitrary streams). sk0 and sk1 are
            # DISTINCT inputs: binding both to one traced array would let
            # the compiler CSE the t0a/t1a temporaries into one
            # [C,TH,WB] buffer and accept a smaller kernel than the
            # production one, which always carries two sk streams.
            def f(pk, rgba, sk0, sk1, ln, thr):
                return fold_chunk_packed(pk, rgba, threshold=thr,
                                         max_k=k, sk0=sk0, sk1=sk1,
                                         length=ln)

            def g(st, rgba, t0, t1, thr):
                return seg_fold_chunk(st, rgba, t0, t1, thr, max_k=k)

            pk = (sds((k, 4, h, w), jnp.float32),
                  sds((k, 2, h, w), jnp.float32),
                  sds((_NSMALL, h, w), jnp.float32))
            jax.jit(f).lower(
                pk, sds((c, 4, h, w), jnp.float32),
                sds((c,), jnp.float32), sds((c,), jnp.float32),
                sds((h, w), jnp.float32),
                sds((h, w), jnp.float32)).compile()
            st = sf.SegFoldState(
                out_color=sds((k, 4, h, w), jnp.float32),
                out_start=sds((k, h, w), jnp.float32),
                out_end=sds((k, h, w), jnp.float32),
                cnt=sds((h, w), jnp.int32),
                prev_rgb=sds((3, h, w), jnp.float32),
                prev_empty=sds((h, w), jnp.bool_))
            jax.jit(g).lower(
                st, sds((c, 4, h, w), jnp.float32),
                sds((c, h, w), jnp.float32), sds((c, h, w), jnp.float32),
                sds((h, w), jnp.float32)).compile()
            ok = True
        except Exception as e:
            from scenery_insitu_tpu import obs

            obs.degrade(
                "ops.seg_fold", "pallas_seg", "seg",
                f"Mosaic rejected the seg fold at k={max_k} chunk={chunk} "
                f"width={width} ({type(e).__name__}: {str(e)[:200]})")
            ok = False
        _PROBE[key] = ok
    return ok


_FUSED_PROBE: dict = {}


def fused_compile_ok(max_k: int = 32, chunk: int = 16,
                     width: int = 2048, stream: bool = False) -> bool:
    """One-time Mosaic-acceptance probe for the shade-in-kernel folds:
    `fused_fold_chunk` (``stream=False``, fold="pallas_fused") and
    `fused_stream_fold` (``stream=True``, fold="fused_stream") at the
    real (K, chunk, width) geometry. The TF constants are baked into the
    kernel but only change scalars, not structure or VMEM, so a generic
    ramp TF probes the same kernel Mosaic judges in production.
    `slicer.make_spec` consults this when a fused fold is explicitly
    requested ON TPU and degrades to the probed pallas_seg/seg stack on
    rejection (ledgered as ops.seg_fold) — same rationale as the auto
    probes: a resource rejection must land here, not inside a traced
    frame step. Off-TPU the fused folds run in interpret mode and are
    never probed."""
    from scenery_insitu_tpu.ops.pallas_util import mosaic_probe

    def compile_fn():
        from scenery_insitu_tpu.core.transfer import TransferFunction

        tf = TransferFunction.ramp(0.0, 1.0, 0.5, "grays")
        k, c, h, w = int(max_k), int(chunk), TILE_H, int(width)
        sds = jax.ShapeDtypeStruct
        pk = (sds((k, 4, h, w), jnp.float32),
              sds((k, 2, h, w), jnp.float32),
              sds((_NSMALL, h, w), jnp.float32))
        if stream:
            s_total = 2 * c           # exercises the multi-chunk grid

            def f(pk, val, ln, ratio, sk0, sk1, thr):
                return fused_stream_fold(pk, val, ln, ratio, sk0,
                                         sk1, thr, max_k=k, chunk=c,
                                         tf=tf, interpret=False)

            jax.jit(f).lower(
                pk, sds((s_total, h, w), jnp.float32),
                sds((h, w), jnp.float32), sds((h, w), jnp.float32),
                sds((s_total,), jnp.float32),
                sds((s_total,), jnp.float32),
                sds((h, w), jnp.float32)).compile()
        else:
            def f(pk, val, ln, ratio, sk0, sk1, thr):
                return fused_fold_chunk(pk, val, ln, ratio, sk0,
                                        sk1, thr, max_k=k, tf=tf,
                                        interpret=False)

            jax.jit(f).lower(
                pk, sds((c, h, w), jnp.float32),
                sds((h, w), jnp.float32), sds((h, w), jnp.float32),
                sds((c,), jnp.float32), sds((c,), jnp.float32),
                sds((h, w), jnp.float32)).compile()

    return mosaic_probe(
        _FUSED_PROBE,
        (jax.default_backend(), int(max_k), int(chunk), int(width),
         bool(stream)),
        compile_fn, "ops.seg_fold",
        "fused_stream" if stream else "pallas_fused", "seg",
        f"Mosaic rejected the fused fold at k={max_k} chunk={chunk} "
        f"width={width} stream={stream}")
