"""Volume sampling primitives (≅ the ``sampleVolume``/``Convert`` shader
segments scenery injects into the raycasters — reference
VDIGenerator.comp:259-261 and AccumulateVDI.comp:4)."""

from __future__ import annotations

import jax.numpy as jnp

from scenery_insitu_tpu.core.volume import Volume


def sample_trilinear(data: jnp.ndarray, pos_xyz: jnp.ndarray) -> jnp.ndarray:
    """Trilinearly sample ``data f32[D, H, W]`` at continuous voxel
    coordinates ``pos_xyz f32[..., 3]`` (x, y, z; voxel centers at
    integer + 0.5). Coordinates are clamped to the border (GL
    CLAMP_TO_EDGE semantics, matching the reference's samplers)."""
    d, h, w = data.shape
    p = pos_xyz - 0.5
    x = jnp.clip(p[..., 0], 0.0, w - 1.0)
    y = jnp.clip(p[..., 1], 0.0, h - 1.0)
    z = jnp.clip(p[..., 2], 0.0, d - 1.0)

    x0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, w - 2) if w > 1 else jnp.zeros_like(x, jnp.int32)
    y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, h - 2) if h > 1 else jnp.zeros_like(y, jnp.int32)
    z0 = jnp.clip(jnp.floor(z).astype(jnp.int32), 0, d - 2) if d > 1 else jnp.zeros_like(z, jnp.int32)
    fx = x - x0
    fy = y - y0
    fz = z - z0

    flat = data.reshape(-1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    z1 = jnp.minimum(z0 + 1, d - 1)

    def at(zi, yi, xi):
        # gather in storage dtype (bf16 render copies keep their halved
        # HBM traffic), accumulate the lerp in f32
        return jnp.take(flat, (zi * h + yi) * w + xi).astype(jnp.float32)

    c000 = at(z0, y0, x0)
    c001 = at(z0, y0, x1)
    c010 = at(z0, y1, x0)
    c011 = at(z0, y1, x1)
    c100 = at(z1, y0, x0)
    c101 = at(z1, y0, x1)
    c110 = at(z1, y1, x0)
    c111 = at(z1, y1, x1)

    c00 = c000 * (1 - fx) + c001 * fx
    c01 = c010 * (1 - fx) + c011 * fx
    c10 = c100 * (1 - fx) + c101 * fx
    c11 = c110 * (1 - fx) + c111 * fx
    c0 = c00 * (1 - fy) + c01 * fy
    c1 = c10 * (1 - fy) + c11 * fy
    return c0 * (1 - fz) + c1 * fz


def sample_volume_world(vol: Volume, world_pos: jnp.ndarray) -> jnp.ndarray:
    """Sample a Volume at world positions ``f32[..., 3]`` (x, y, z)."""
    return sample_trilinear(vol.data, vol.world_to_voxel(world_pos))


def intersect_aabb(origin: jnp.ndarray, dirs: jnp.ndarray,
                   box_min: jnp.ndarray, box_max: jnp.ndarray):
    """Slab-method ray/AABB intersection (≅ intersectBoundingBox,
    VDIGenerator.comp:333-347).

    origin f32[3], dirs f32[3, ...]; returns (tnear, tfar) each f32[...];
    a miss yields tnear > tfar."""
    inv = 1.0 / jnp.where(jnp.abs(dirs) < 1e-12,
                          jnp.where(dirs < 0, -1e-12, 1e-12), dirs)
    o = origin.reshape((3,) + (1,) * (dirs.ndim - 1))
    t0 = (box_min.reshape(o.shape) - o) * inv
    t1 = (box_max.reshape(o.shape) - o) * inv
    tmin = jnp.minimum(t0, t1)
    tmax = jnp.maximum(t0, t1)
    tnear = jnp.max(tmin, axis=0)
    tfar = jnp.min(tmax, axis=0)
    return jnp.maximum(tnear, 0.0), tfar


def adjust_opacity(alpha: jnp.ndarray, length_ratio) -> jnp.ndarray:
    """Opacity correction for a sampling interval whose length differs from
    the nominal one: ``1 - (1 - a)^ratio`` (≅ adjustOpacity,
    VDIGenerator.comp:80-82)."""
    return 1.0 - jnp.power(jnp.clip(1.0 - alpha, 1e-7, 1.0), length_ratio)
