"""Frame-coherent occupancy pyramid — the empty-space acceleration
structure of the MXU slice march (≅ the reference's OctreeCells grid,
VDIGenerator.comp:232-254 + GridCellsToZero.comp, which it rebuilds by
atomic-add during every generation pass; here the structure is VALUE
RANGES, built once per frame and shared).

Three ideas, layered:

1. **One structure per frame, not one reduction per march.** The legacy
   path (`slicer.occupancy_for`) re-ran `permute_volume` plus a
   full-volume reduction at every call site — the counting march, the
   writing march, the temporal seeder and the plain render each paid an
   extra HBM sweep. `pyramid_from_volume` computes the two-level pyramid
   (per-chunk and per-(chunk × v-tile) value ranges, with the one-row
   apron argument of `slicer.chunk_occupancy_vtiles`) ONCE, on a permuted
   volume it can share with the march itself, and every consumer reads
   the same arrays.

2. **Ranges, not booleans.** The pyramid stores per-cell [lo, hi] value
   ranges of the field; occupancy gates are derived by pushing the range
   through the transfer function's conservative bound
   (`tf.max_alpha_in`). Ranges are TF-independent, so the same pyramid
   serves any number of marches, transfer functions, and the load
   histogram — and they can come from somewhere cheaper than a volume
   sweep:

3. **Sim-fused updates.** The time-fused Gray-Scott stencil
   (sim/pallas_stencil.py) already touches every voxel of the field each
   step; its optional ranges epilogue emits per-(z, y)-brick min/max of
   the rendered field as (1, 1) SMEM reductions riding the same kernel —
   near-free. `pyramid_from_ranges` maps those DATA-layout brick ranges
   onto the MARCH-layout (chunk × v-tile) cells of any `AxisSpec`
   conservatively (outward-rounded brick intervals, apron rows included,
   zero admitted for padded chunks, a bf16 widening when the march reads
   a bf16 copy), so a frame can skip empty space without ever re-reading
   the volume. When the Pallas path degrades, `field_ranges` is the lax
   fallback reduction (one sweep of the field in data layout — still
   cheaper than permute + reduce, and routed through ``obs.degrade``).

The same per-rank pyramid also drives the sort-last fold: its live
fraction is the per-rank load histogram behind
``CompositeConfig.k_budget = "occupancy"`` (`k_budget_target`), which
re-targets the adaptive supersegment count so sparse slabs stop chasing
the same K as the densest rank (docs/PERF.md "Empty-space skipping").

Conservativeness contract (property-tested in tests/test_occupancy.py):
a cell the pyramid gates off is PROVABLY zero-alpha — in-plane bilinear
resampling keeps values inside each covered row-pair's range (the apron
makes every adjacent-row pair fully contained in at least one band), and
`max_alpha_in` bounds any transfer function, band-pass included, over
the whole interval.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from scenery_insitu_tpu import obs

# one storage rounding of a bf16 march copy moves a value by at most
# 2^-8 relative (8 mantissa bits); ranges built from the f32 sim field
# widen by this before gating a bf16 march (pyramid_from_ranges)
_BF16_EPS = 2.0 ** -8


class FieldRanges(NamedTuple):
    """Per-brick value ranges of a scalar field in DATA layout
    ``[D, H, W]``: brick (i, j) covers ``z ∈ [i*bz, (i+1)*bz) ×
    y ∈ [j*by, (j+1)*by) × all x`` where ``bz = D // lo.shape[0]`` and
    ``by = H // lo.shape[1]`` (brick sizes are derived from shapes — the
    arrays ARE the structure, so they ride jit boundaries and scan
    carries as plain pytrees)."""

    lo: jnp.ndarray   # f32[nzb, nyb]
    hi: jnp.ndarray   # f32[nzb, nyb]


def default_bricks(shape: Tuple[int, int, int]) -> Tuple[int, int]:
    """Canonical (nzb, nyb) brick grid for a field shape: ~32 z bricks ×
    ~OCCUPANCY_VTILES_DEFAULT y bricks, snapped down to divisors so
    reshaping reductions stay exact. Matches the flagship march geometry
    (chunk=16 slices at 512^3 → bz=16 aligns with chunks; the y-brick
    cap tracks the benched vtile count)."""
    from scenery_insitu_tpu.config import OCCUPANCY_VTILES_DEFAULT

    d, h, _ = shape
    return _cap_divisor(d, 32), _cap_divisor(h, OCCUPANCY_VTILES_DEFAULT)


def _cap_divisor(n: int, cap: int) -> int:
    b = min(n, cap)
    while n % b:
        b -= 1
    return b


def field_ranges(field: jnp.ndarray, nzb: int, nyb: int) -> FieldRanges:
    """Lax fallback reduction: per-brick min/max of ``field [D, H, W]``
    in one sweep of the data layout (no permute). Requires ``nzb | D``
    and ``nyb | H``; x is fully reduced (the lane axis the fused-stencil
    epilogue cannot split either)."""
    d, h, w = field.shape
    if d % nzb or h % nyb:
        raise ValueError(f"brick grid ({nzb}, {nyb}) does not divide "
                         f"field shape {field.shape}")
    x = field.reshape(nzb, d // nzb, nyb, h // nyb, w).astype(jnp.float32)
    return FieldRanges(lo=jnp.min(x, axis=(1, 3, 4)),
                       hi=jnp.max(x, axis=(1, 3, 4)))


def remap_ranges(lo: jnp.ndarray, hi: jnp.ndarray,
                 to_shape: Tuple[int, int]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Re-grid brick ranges to another brick count per axis,
    conservatively: reducing (from % to == 0) is exact, refining
    (to % from == 0) repeats the coarse range over its fine bricks, and
    incommensurate grids reduce to their gcd granularity first (e.g. a
    6-brick kernel grid onto a 32-brick canonical grid keeps 2 bands
    instead of collapsing to one global range) — a REAL coarsening
    either way, so it lands on the fallback ledger. Used to normalize
    the fused-stencil epilogue's native (tz, th) granularity onto the
    caller's canonical grid so shapes stay fixed across the greedy
    multi-T decomposition."""
    import math

    def one_axis(x, n_to, axis, red):
        n_from = x.shape[axis]
        if n_from == n_to:
            return x
        if n_from % n_to == 0:
            r = n_from // n_to
            shp = x.shape[:axis] + (n_to, r) + x.shape[axis + 1:]
            return red(x.reshape(shp), axis=axis + 1)
        if n_to % n_from != 0:
            # incommensurate: coarsen to the gcd granularity (>= 1),
            # then refine — structure survives at g bands instead of
            # one global range. Static condition -> trace-time ledger.
            g = math.gcd(n_from, n_to)
            obs.degrade("occupancy.ranges_remap", f"{n_from} bricks",
                        f"{g} bands",
                        f"kernel brick grid {n_from} incommensurate "
                        f"with canonical {n_to} on axis {axis} — "
                        f"occupancy resolution coarsens", warn=False)
            shp = x.shape[:axis] + (g, n_from // g) + x.shape[axis + 1:]
            x = red(x.reshape(shp), axis=axis + 1)
            n_from = g
        return jnp.repeat(x, n_to // n_from, axis=axis)

    for axis in (0, 1):
        lo = one_axis(lo, to_shape[axis], axis, jnp.min)
        hi = one_axis(hi, to_shape[axis], axis, jnp.max)
    return lo, hi


# ----------------------------------------------------------- the pyramid


class OccupancyPyramid(NamedTuple):
    """Two-level march-layout occupancy for one (volume, AxisSpec) pair.

    Level 0: per-(chunk × v-tile) cell value ranges ``lo/hi
    f32[nchunks, nt]`` (pre-shaded RGBA volumes store ALPHA ranges) and
    the derived gate ``tiles bool[nchunks, nt]``. Level 1: the per-chunk
    gate ``chunks bool[nchunks]`` derived from the union of the cell
    ranges (aprons only widen within a chunk, so it equals the
    whole-slab reduction exactly). ``nt == 1`` when the spec does no
    in-plane tiling."""

    lo: jnp.ndarray       # f32[nchunks, nt]
    hi: jnp.ndarray       # f32[nchunks, nt]
    chunks: jnp.ndarray   # bool[nchunks]
    tiles: jnp.ndarray    # bool[nchunks, nt]

    def gate(self, spec):
        """The structure `slicer.slice_march` consumes for ``spec``:
        None when skipping is off, bool[nchunks] for chunk-only
        skipping, (chunks, tiles) when the spec tiles in-plane — the
        same contract `slicer.occupancy_for` always had."""
        if not spec.skip_empty:
            return None
        if spec.vtiles > 0:
            return self.chunks, self.tiles
        return self.chunks

    def live_fraction(self) -> jnp.ndarray:
        """f32[] fraction of level-0 cells that can contribute opacity —
        the per-rank load signal of the occupancy K budget and the bench
        artifact's headline sparsity number."""
        return jnp.mean(self.tiles.astype(jnp.float32))

    def chunk_live_fractions(self) -> jnp.ndarray:
        """f32[nchunks] per-chunk live-tile fraction (the histogram
        axis benchmark artifacts record)."""
        return jnp.mean(self.tiles.astype(jnp.float32), axis=1)


def resolved_tiles(spec, nv: int) -> int:
    """The tile count a march over a volume with ``nv`` v-rows actually
    uses: ``spec.vtiles`` re-clamped so every band keeps >= 2 rows
    (distributed slabs can be far smaller than the global shape
    `make_spec` clamped against). A clamp that REDUCES the configured
    count is recorded on the fallback ledger — it silently coarsens the
    skip granularity (ISSUE 6 satellite; the old path said nothing)."""
    if spec.vtiles <= 0:
        return 1
    nt = max(1, min(spec.vtiles, nv // 2))
    if nt < spec.vtiles:
        obs.degrade("occupancy.vtiles_clamp", str(spec.vtiles), str(nt),
                    f"v extent {nv} supports at most {max(1, nv // 2)} "
                    f"bands of >= 2 rows (tiny distributed slab?)",
                    warn=False)
    return nt


def _tile_bands(nv: int, nt: int):
    """Row intervals [r0, r1) of the nt v-tiles INCLUDING the one-row
    apron (see slicer.chunk_occupancy_vtiles: an output row's bilinear
    support may straddle a band boundary; the apron makes every
    adjacent-row pair fully contained in at least one band). The last
    band absorbs the remainder."""
    tv = nv // nt
    return [(max(t * tv - 1, 0),
             nv if t == nt - 1 else min((t + 1) * tv + 1, nv))
            for t in range(nt)]


def _gates(tf, lo, hi, pre_shaded: bool, alpha_eps: float):
    """(chunks, tiles) gates from cell ranges. Scalar volumes push the
    range through the TF's conservative alpha bound; pre-shaded volumes
    gate on the stored alpha directly."""
    if pre_shaded:
        tiles = hi > alpha_eps
        return jnp.any(tiles, axis=1), tiles
    cl = lambda x: jnp.clip(x, 0.0, 1.0)
    tiles = tf.max_alpha_in(cl(lo), cl(hi)) > alpha_eps
    chunks = tf.max_alpha_in(cl(jnp.min(lo, axis=1)),
                             cl(jnp.max(hi, axis=1))) > alpha_eps
    return chunks, tiles


def pyramid_from_volume(vol, tf, spec, volp: Optional[jnp.ndarray] = None,
                        alpha_eps: float = 1e-5,
                        ntiles: Optional[int] = None) -> OccupancyPyramid:
    """Build the pyramid from the volume itself — ONE pass over the
    march-layout copy, exact ranges. ``volp`` (the UNPADDED
    `slicer.permute_volume` output) lets the caller share the single
    per-frame permuted copy between this pass and the marches; chunk
    boundaries come from the shared `slicer._pad_to_chunks`, so the
    pyramid and the march can never disagree on slab layout.

    ``ntiles`` overrides the spec-derived tile count (used by the legacy
    `slicer.chunk_occupancy` wrapper, which is the nt=1 level alone)."""
    from scenery_insitu_tpu.ops import slicer

    rec = obs.get_recorder()
    if volp is None:
        volp = slicer.permute_volume(vol, spec)
    pre_shaded = vol.data.ndim == 4
    if pre_shaded:
        volp = volp[:, 3]                                  # alpha plane
    volp, nchunks = slicer._pad_to_chunks(volp, spec.chunk)
    nv = volp.shape[1]
    nt = resolved_tiles(spec, nv) if ntiles is None else max(1, ntiles)
    los, his = [], []
    for r0, r1 in _tile_bands(nv, nt):
        band = volp[:, r0:r1].reshape(nchunks, -1)
        # reduce in storage dtype (bf16 march copies), gate in f32
        los.append(jnp.min(band, axis=1).astype(jnp.float32))
        his.append(jnp.max(band, axis=1).astype(jnp.float32))
    lo = jnp.stack(los, axis=1)                            # [nchunks, nt]
    hi = jnp.stack(his, axis=1)
    chunks, tiles = _gates(tf, lo, hi, pre_shaded, alpha_eps)
    rec.count("occupancy_pyramid_builds")
    rec.event("occupancy_build", source="volume", nchunks=int(nchunks),
              ntiles=int(nt))
    return OccupancyPyramid(lo, hi, chunks, tiles)


def pyramid_from_ranges(ranges: FieldRanges, vol, tf, spec,
                        alpha_eps: float = 1e-5) -> OccupancyPyramid:
    """Build the pyramid from sim-provided DATA-layout brick ranges —
    zero volume traffic. ``ranges`` must describe exactly the field the
    volume wraps (``vol.data`` shape ``[D, H, W]``, scalar; the
    distributed slab path with its halo rows keeps `pyramid_from_volume`
    instead).

    Conservative by construction: each (chunk × v-tile) cell takes the
    union range of every brick its region (apron rows included, padded
    slices admitting zero) can touch, with brick intervals rounded
    outward; a bf16 march copy (``spec.render_dtype``) additionally
    widens the range by one storage rounding. Cells this pyramid gates
    off are a SUBSET of what `pyramid_from_volume` gates off — coarser
    skipping, identical output (the march's skip path is exact)."""
    if vol.data.ndim == 4:
        raise ValueError("sim field ranges describe a scalar field; "
                         "pre-shaded RGBA volumes build from the volume")
    d, h, w = vol.data.shape
    nzb, nyb = ranges.lo.shape
    if d % nzb or h % nyb:
        raise ValueError(f"brick grid {ranges.lo.shape} does not divide "
                         f"volume shape {vol.data.shape}")
    bz, by = d // nzb, h // nyb
    a = spec.axis

    # orient the brick grid as [slice-axis bricks, v-axis bricks]
    if a == 2:            # march z, v = y
        sl_lo, sl_hi = ranges.lo, ranges.hi
        sb, s_total, vb = bz, d, by
    elif a == 1:          # march y, v = z
        sl_lo, sl_hi = ranges.lo.T, ranges.hi.T
        sb, s_total, vb = by, h, bz
    else:                 # march x: bricks don't resolve x — one global
        #                   slice brick; in-plane tiles still resolve z
        sl_lo = jnp.min(ranges.lo, axis=1)[None, :]        # [1, nzb]
        sl_hi = jnp.max(ranges.hi, axis=1)[None, :]
        sb, s_total, vb = w, w, bz

    c = spec.chunk
    nchunks = -(-s_total // c)
    nv = vol.data.shape[_data_dim(spec.v_axis)]
    nt = resolved_tiles(spec, nv)

    # per-tile band ranges along the v bricks (apron rows included)
    band_lo, band_hi = [], []
    for r0, r1 in _tile_bands(nv, nt):
        b0, b1 = r0 // vb, -(-r1 // vb)
        band_lo.append(jnp.min(sl_lo[:, b0:b1], axis=1))
        band_hi.append(jnp.max(sl_hi[:, b0:b1], axis=1))
    band_lo = jnp.stack(band_lo, axis=1)                   # [nsb, nt]
    band_hi = jnp.stack(band_hi, axis=1)

    # per-chunk reduction along the slice-axis bricks: marched slice
    # interval -> data interval (sign flip) -> outward brick interval
    los, his = [], []
    for ci in range(nchunks):
        m0, m1 = ci * c, min((ci + 1) * c, s_total)
        d0, d1 = (m0, m1) if spec.sign > 0 else (s_total - m1, s_total - m0)
        b0, b1 = d0 // sb, -(-d1 // sb)
        lo_c = jnp.min(band_lo[b0:b1], axis=0)
        hi_c = jnp.max(band_hi[b0:b1], axis=0)
        if (ci + 1) * c > s_total:
            # the shared _pad_to_chunks zero-pads the last chunk: zero
            # enters its value range
            lo_c = jnp.minimum(lo_c, 0.0)
            hi_c = jnp.maximum(hi_c, 0.0)
        los.append(lo_c)
        his.append(hi_c)
    lo = jnp.stack(los)                                    # [nchunks, nt]
    hi = jnp.stack(his)
    if spec.render_dtype == "bf16":
        # the march reads a bf16 COPY of the f32 field these ranges
        # describe — one storage rounding can push a voxel past the f32
        # extremum, so widen before gating
        lo = lo - jnp.abs(lo) * _BF16_EPS
        hi = hi + jnp.abs(hi) * _BF16_EPS
    chunks, tiles = _gates(tf, lo, hi, False, alpha_eps)
    rec = obs.get_recorder()
    rec.count("occupancy_ranges_builds")
    rec.event("occupancy_build", source="sim_ranges",
              nchunks=int(nchunks), ntiles=int(nt))
    return OccupancyPyramid(lo, hi, chunks, tiles)


def _data_dim(axis_xyz: int) -> int:
    # xyz axis index -> Volume.data dim counted from the end (mirrors
    # slicer._DATA_DIM without importing the module at call time)
    return {0: -1, 1: -2, 2: -3}[axis_xyz]


# ------------------------------------------------------ load-aware K budget


def k_budget_target(live_frac, total_live, n_ranks: int, k: int,
                    k_min: int = 4) -> jnp.ndarray:
    """f32[] per-rank adaptive segment-count target under
    ``CompositeConfig.k_budget = "occupancy"``: this rank's share of the
    mesh-wide budget ``n_ranks * k``, proportional to its occupancy-
    pyramid live fraction, clamped to ``[k_min, k]``.

    Array SHAPES stay at K on every rank (one SPMD program), so this is
    a quality/work re-balance, not a memory one: the adaptive threshold
    controller closes ~k_r segments on rank r instead of chasing K
    everywhere — sparse slabs emit coarser VDIs (their content cannot
    fill K slots anyway; slots they don't start stay +inf and cost the
    exchange nothing after qpack8), while dense slabs keep full fidelity
    and stop being the only rank whose march runs at the knife edge of
    the shared threshold band (docs/PERF.md "Empty-space skipping").
    An all-empty mesh (total ~ 0) degenerates to the static budget."""
    live_frac = jnp.asarray(live_frac, jnp.float32)
    total = jnp.maximum(jnp.asarray(total_live, jnp.float32), 1e-12)
    share = n_ranks * k * live_frac / total
    share = jnp.where(total > 1e-9, share, jnp.float32(k))
    return jnp.clip(share, jnp.float32(min(k_min, k)), jnp.float32(k))


# -------------------------------------------- uneven z-slab render plans


# Work model of one z slice (docs/PERF.md "Render rebalancing"): a live
# slice costs 1 + base, an empty one only base — skipping makes air
# cheap, not free (the chunk scan still iterates, the pyramid gate still
# evaluates, padded fold chunks still close segments). The committed CPU
# A/B (benchmarks/results/rebalance_ab_r10_cpu.json) is the measured
# anchor for the modeled straggler factors derived from this.
PLAN_BASE_COST = 0.05


def z_live_profile(field: jnp.ndarray, tf, nzb: int = 0, nyb: int = 0,
                   alpha_eps: float = 1e-5) -> jnp.ndarray:
    """f32[nzb] per-z-brick live fraction of a scalar field ``[D, H, W]``
    — the host-side re-plan signal of ``CompositeConfig.rebalance ==
    "occupancy"``. One `field_ranges` sweep in data layout (no permute)
    gated through the TF's conservative alpha bound, reduced over the
    in-plane bricks: entry i is the fraction of (y-brick) cells in
    z band ``[i*D/nzb, (i+1)*D/nzb)`` that can contribute opacity.
    ``nzb``/``nyb`` default to `default_bricks`. In the distributed
    session each rank runs this on its EVEN slab and the profiles
    concatenate along the mesh axis into the global z profile
    `slice_plan` consumes."""
    d_nzb, d_nyb = default_bricks(field.shape)
    nzb = nzb or d_nzb
    nyb = nyb or d_nyb
    fr = field_ranges(field, nzb, nyb)
    cl = lambda x: jnp.clip(x, 0.0, 1.0)
    live = tf.max_alpha_in(cl(fr.lo), cl(fr.hi)) > alpha_eps
    return jnp.mean(live.astype(jnp.float32), axis=1)


def z_range_profile(field: jnp.ndarray, nzb: int = 0):
    """(lo f32[nzb], hi f32[nzb]) per-z-brick sampled value range of a
    scalar field ``[D, H, W]``, clipped to the TF's [0, 1] domain — the
    host-side signal of the LOD planner's TF-straddle coarsening gate
    (`parallel.lod.select_levels`; docs/PERF.md "LOD marching"): a brick
    whose range crosses an opacity edge must keep level 0, and the
    decision needs the range itself, not the live reduction
    `z_live_profile` collapses it to. One `field_ranges` sweep with a
    single in-plane brick (the gate is per z-brick). In the distributed
    session each rank profiles its EVEN slab and the ranges concatenate
    along the mesh axis."""
    nzb = nzb or default_bricks(field.shape)[0]
    fr = field_ranges(field, nzb, 1)
    return (jnp.clip(fr.lo[:, 0], 0.0, 1.0),
            jnp.clip(fr.hi[:, 0], 0.0, 1.0))


def _slice_work(live_profile, d: int, base_cost: float):
    """f64[d] per-slice march work from a per-z-bin live profile
    (``len(live_profile)`` must divide ``d``)."""
    import numpy as np

    prof = np.asarray(live_profile, np.float64).clip(0.0, None)
    nb = prof.shape[0]
    if nb == 0 or d % nb:
        raise ValueError(f"live profile has {nb} bins which do not "
                         f"divide depth {d}")
    return np.repeat(prof, d // nb) + base_cost


def slice_plan(live_profile, d: int, n: int, min_depth: int = 1,
               quantum: int = 1, prev=None, hysteresis: float = 0.0,
               base_cost: float = PLAN_BASE_COST,
               max_depth: int = 0):
    """Per-rank contiguous z-slice counts equalizing live march work
    (docs/PERF.md "Render rebalancing") — host-side, numpy, static.

    ``live_profile`` (f32[nb], nb | d) is the global per-z-bin live
    fraction (`z_live_profile`, rank profiles concatenated). Greedy
    prefix-sum equalization places band boundary r at the slice where
    cumulative work first reaches r/n of the total, snapped to the
    nearest ``quantum`` multiple and clamped so every band keeps
    ``min_depth`` slices. Conservation is structural: boundaries are a
    monotone ladder from 0 to d, so ``sum(plan) == d`` always.

    ``max_depth`` caps any band's depth (0 = the default cap,
    ``2 * ceil(d / n)``): shard_map pads every rank's band to
    ``max(plan)``, so an unbounded plan — one rank owning a huge empty
    region — would make EVERY rank scan (and skip) that many chunks;
    the cap bounds the padding tax at the cost of splitting large empty
    regions across several ranks (air is cheap to share).

    ``prev``/``hysteresis`` stabilize the plan across frames: when every
    boundary of the fresh plan is within ``hysteresis * (d / n)`` slices
    of ``prev``'s, ``prev`` is returned UNCHANGED (object-equal), so the
    caller can key recompiles on plan identity. Returns a tuple of n
    ints."""
    import numpy as np

    if n < 1:
        raise ValueError(f"need >= 1 rank, got {n}")
    min_depth = max(1, min(int(min_depth), d // n))
    quantum = max(1, int(quantum))
    max_depth = int(max_depth) or 2 * (-(-d // n))
    max_depth = max(max_depth, -(-d // n))          # keep n bands feasible
    w = _slice_work(live_profile, d, base_cost)
    cw = np.cumsum(w)
    total = float(cw[-1])
    bounds = [0]
    for r in range(1, n):
        target = total * r / n
        z = int(np.searchsorted(cw, target, side="left")) + 1
        z = int(round(z / quantum)) * quantum
        lo = max(bounds[-1] + min_depth, d - (n - r) * max_depth)
        hi = min(d - (n - r) * min_depth, bounds[-1] + max_depth)
        bounds.append(int(min(max(z, lo), hi)))
    bounds.append(d)
    plan = tuple(int(b1 - b0) for b0, b1 in zip(bounds, bounds[1:]))
    if prev is not None and len(prev) == n and hysteresis > 0.0:
        pb = np.concatenate([[0], np.cumsum(np.asarray(prev, np.int64))])
        if pb[-1] == d and np.max(np.abs(np.asarray(bounds) - pb)) \
                <= hysteresis * d / n:
            return tuple(int(p) for p in prev)
    return plan


def even_plan(d: int, n: int):
    """The identity render plan: the even z-slab split itself."""
    if d % n:
        raise ValueError(f"depth {d} not divisible by {n} ranks")
    return (d // n,) * n


def plan_work(live_profile, d: int, plan,
              base_cost: float = PLAN_BASE_COST):
    """Per-rank modeled march work of a render plan under the slice work
    model — the numerator of the straggler factor."""
    import numpy as np

    w = _slice_work(live_profile, d, base_cost)
    if sum(plan) != d:
        raise ValueError(f"plan {plan} does not cover depth {d}")
    bounds = np.concatenate([[0], np.cumsum(np.asarray(plan, np.int64))])
    return [float(w[b0:b1].sum()) for b0, b1 in zip(bounds, bounds[1:])]


def straggler_factor(live_profile, d: int, plan,
                     base_cost: float = PLAN_BASE_COST) -> float:
    """max/mean per-rank modeled march work — the frame-barrier term the
    rebalance attacks (frame time is the max over ranks; mean is the
    perfectly-balanced floor). 1.0 = no straggler."""
    import numpy as np

    work = plan_work(live_profile, d, plan, base_cost)
    return float(np.max(work) / max(np.mean(work), 1e-12))
