"""Quantized supersegment wire formats for the sort-last exchange
(docs/PERF.md "Wire formats").

The sort-last composite ships every supersegment as 6 f32 lanes
(24 B/slot) over ICI — in BOTH exchange schedules the per-rank wire
traffic is ``(n-1)·K·H·(W/n)·24`` bytes per frame, and the PERF.md H2
evidence says traffic-total reduction is the lever that pays on this
platform. The reference system compresses VDIs before they cross process
boundaries; the related compositing work does the same in flight (the
Distributed FrameBuffer compresses every tile message, Usher et al.;
deep compositing of unstructured data quantizes fragment payloads,
Morrical et al.). Over ICI a byte-stream codec is off the table
(collectives move typed arrays), so the equivalent lever is a narrower
**element encoding** applied just before the collective and decoded just
after it:

``f32``     the identity — 24 B/slot, bit-exact (the default; the f32
            code path is exactly the pre-wire pipeline).
``bf16``    color + depth lanes cast to bfloat16 — 12 B/slot (2×).
            ``+inf`` empty-slot depths survive the cast exactly; finite
            values lose 16 mantissa bits (monotone rounding, so sorted
            streams stay sorted).
``qpack8``  premultiplied RGBA packed to u8 unorm in one u32 lane
            (4 B/slot) and the (start, end) depth pair quantized to u8
            each against per-fragment ``[near, far]`` f32 scalars
            carried alongside, packed into one u16 lane (2 B/slot) —
            6 B/slot, 4×. The u16 sentinel ``0xFFFF`` (byte sentinel
            ``0xFF`` per depth) is reserved to round-trip ``+inf``
            empty slots EXACTLY, so the merge/re-segmentation empty-slot
            convention (``ops.composite``) is untouched; live bytes are
            clamped to ``0..254`` so no live pair can collide with the
            sentinel. The start byte occupies the high half, so u16
            ordering == (start, end) lexicographic ordering.

Quantized modes are lossy BY CONTRACT: the quantization error is bounded
by one color quantum (1/255 per channel) and one depth quantum
(fragment depth span / 254). Because each rank normalizes against its
OWN fragment's [near, far] — a z-slab's ray-parameter range, roughly 1/n
of the scene's — the effective depth resolution scales with the mesh.
Both quantizers are monotone, so a per-pixel depth-sorted stream decodes
depth-sorted (the pairwise-merge precondition of the ring schedule).

The numpy twins (``qpack8_quantize_np``/``qpack8_dequantize_np``) are
the host-side reuse of the same format: ``io.vdi_io.save_vdi`` and
``runtime.streaming.VDIPublisher`` run them as a pre-codec pass so the
disk/DCN hop gets the same 4× before zstd/zlib even sees the buffer.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

WIRE_FORMATS = ("f32", "bf16", "qpack8")

# precision codes for VDIMetadata.precision / stored-artifact tags
WIRE_CODES = {"f32": 0, "qpack8": 1, "bf16": 2}

# per-supersegment-slot wire bytes: (color, depth). f32: 4 lanes * 4 B +
# 2 lanes * 4 B; bf16 halves both; qpack8 is one u32 color lane + one
# u16 packed depth-pair lane. Consumed by the traffic model
# (ops.composite.modeled_exchange_traffic).
WIRE_SLOT_BYTES = {"f32": (16, 8), "bf16": (8, 4), "qpack8": (4, 2)}

_QMAX = 254          # live depth codes span 0..254; 255 is the +inf sentinel
_SENTINEL = 255


def wire_slot_bytes(wire: str) -> Tuple[int, int]:
    """(color_bytes, depth_bytes) one supersegment slot costs on the wire."""
    try:
        return WIRE_SLOT_BYTES[wire]
    except KeyError:
        raise ValueError(f"unknown wire format {wire!r}; have {WIRE_FORMATS}") from None


def _count_encode(wire: str, cb: int, db: int) -> None:
    """Host-side trace-time marker: one per encoded fragment build
    (docs/OBSERVABILITY.md wire counters)."""
    from scenery_insitu_tpu import obs as _obs

    rec = _obs.get_recorder()
    rec.count("wire_encode_builds")
    rec.event("wire_encode", wire=wire, bytes_per_slot=cb + db)


def _depth_scale(depth: jnp.ndarray):
    """Per-fragment [near, far] over the finite depths, pinned to [0, 1]
    when the fragment is fully empty and to a unit span when near == far
    so the quantize arithmetic stays finite. Returns
    (finite_mask, near, far, enc_span)."""
    finite = jnp.isfinite(depth)
    near = jnp.min(jnp.where(finite, depth, jnp.inf))
    far = jnp.max(jnp.where(finite, depth, -jnp.inf))
    ok = jnp.isfinite(near) & jnp.isfinite(far)
    near = jnp.where(ok, near, jnp.float32(0.0))
    far = jnp.where(ok, far, jnp.float32(1.0))
    span = far - near
    enc_span = jnp.where(span > 0, span, jnp.float32(1.0))
    return finite, near, far, enc_span


def _bcast_scale(scale: jnp.ndarray, ndim: int):
    """Split a [..., 2] scale into (near, far) reshaped to broadcast
    against an ndim-D encoded array (leading batch dims align)."""
    near, far = scale[..., 0], scale[..., 1]
    pad = (1,) * (ndim - near.ndim)
    return near.reshape(near.shape + pad), far.reshape(far.shape + pad)


def _pack_rgba(color: jnp.ndarray) -> jnp.ndarray:
    """[..., 4, H, W] f32 in [0, 1] → u32[..., H, W] (R|G<<8|B<<16|A<<24)."""
    c8 = jnp.round(jnp.clip(color, 0.0, 1.0) * 255.0).astype(jnp.uint32)
    return (c8[..., 0, :, :] | (c8[..., 1, :, :] << 8)
            | (c8[..., 2, :, :] << 16) | (c8[..., 3, :, :] << 24))


def _unpack_rgba(enc: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`_pack_rgba` → f32[..., 4, H, W]."""
    return jnp.stack([(enc >> s) & 0xFF for s in (0, 8, 16, 24)],
                     axis=-3).astype(jnp.float32) / 255.0


# ------------------------------------------------------------- VDI fragments

def encode_fragment(color: jnp.ndarray, depth: jnp.ndarray, wire: str
                    ) -> Tuple[jnp.ndarray, jnp.ndarray,
                               Optional[jnp.ndarray]]:
    """Encode one VDI fragment (color [..., 4, H, W] premultiplied f32,
    depth [..., 2, H, W] f32 with +inf empty slots) for the wire.

    Returns ``(color_enc, depth_enc, scale)``. ``scale`` is the
    ``f32[2]`` per-fragment ``[near, far]`` depth normalization (qpack8
    only; None otherwise) — it must travel WITH the fragment (ppermute it
    alongside, or all_gather it across the all_to_all). For qpack8 the
    channel axes are packed away: color → u32[..., H, W]
    (R | G<<8 | B<<16 | A<<24), depth → u16[..., H, W]
    (start_q<<8 | end_q)."""
    if wire == "f32":
        return color, depth, None
    if wire == "bf16":
        _count_encode(wire, *WIRE_SLOT_BYTES[wire])
        return (color.astype(jnp.bfloat16), depth.astype(jnp.bfloat16),
                None)
    if wire != "qpack8":
        raise ValueError(f"unknown wire format {wire!r}; have {WIRE_FORMATS}")
    _count_encode(wire, *WIRE_SLOT_BYTES[wire])

    finite, near, far, enc_span = _depth_scale(depth)
    q = jnp.clip(jnp.round((depth - near) / enc_span * _QMAX), 0.0,
                 float(_QMAX))
    q = jnp.where(finite, q, float(_SENTINEL)).astype(jnp.uint16)
    d16 = (q[..., 0, :, :] << 8) | q[..., 1, :, :]          # u16[..., H, W]
    return _pack_rgba(color), d16, jnp.stack([near, far])


def decode_fragment(color_enc: jnp.ndarray, depth_enc: jnp.ndarray,
                    scale: Optional[jnp.ndarray], wire: str
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`encode_fragment` → f32 (color [..., 4, H, W],
    depth [..., 2, H, W]). ``scale`` may carry leading batch dims
    ([..., 2], e.g. [n, 2] per-source after an all_to_all + all_gather)
    that broadcast against the fragment's leading dims."""
    if wire == "f32":
        return color_enc, depth_enc
    if wire == "bf16":
        return (color_enc.astype(jnp.float32),
                depth_enc.astype(jnp.float32))
    if wire != "qpack8":
        raise ValueError(f"unknown wire format {wire!r}; have {WIRE_FORMATS}")

    near, far = _bcast_scale(scale, depth_enc.ndim)
    span = jnp.maximum(far - near, 0.0)

    qs = (depth_enc >> 8).astype(jnp.float32)
    qe = (depth_enc & 0xFF).astype(jnp.float32)
    ds = jnp.where((depth_enc >> 8) == _SENTINEL, jnp.inf,
                   near + qs / _QMAX * span)
    de = jnp.where((depth_enc & 0xFF) == _SENTINEL, jnp.inf,
                   near + qe / _QMAX * span)
    return _unpack_rgba(color_enc), jnp.stack([ds, de], axis=-3)


# ------------------------------------------------------ plain-image fragments

def encode_plain(image: jnp.ndarray, depth: jnp.ndarray, wire: str
                 ) -> Tuple[jnp.ndarray, jnp.ndarray,
                            Optional[jnp.ndarray]]:
    """Wire-encode a plain fragment (image [..., 4, H, W] premultiplied,
    depth [..., H, W] with +inf empty pixels). qpack8 here is
    RGBA→u32 + ONE u16 depth per pixel over the full 0..65534 range
    (sentinel 0xFFFF = +inf) — the single plain depth gets the whole u16
    instead of sharing it with an end depth."""
    if wire == "f32":
        return image, depth, None
    if wire == "bf16":
        _count_encode(wire, *WIRE_SLOT_BYTES[wire])
        return (image.astype(jnp.bfloat16), depth.astype(jnp.bfloat16),
                None)
    if wire != "qpack8":
        raise ValueError(f"unknown wire format {wire!r}; have {WIRE_FORMATS}")
    _count_encode(wire, *WIRE_SLOT_BYTES[wire])

    qmax = 65534.0                       # 0xFFFF is the +inf sentinel
    finite, near, far, enc_span = _depth_scale(depth)
    q = jnp.clip(jnp.round((depth - near) / enc_span * qmax), 0.0, qmax)
    d16 = jnp.where(finite, q, 65535.0).astype(jnp.uint16)
    return _pack_rgba(image), d16, jnp.stack([near, far])


def decode_plain(image_enc: jnp.ndarray, depth_enc: jnp.ndarray,
                 scale: Optional[jnp.ndarray], wire: str
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`encode_plain` → f32 (image [..., 4, H, W],
    depth [..., H, W])."""
    if wire == "f32":
        return image_enc, depth_enc
    if wire == "bf16":
        return (image_enc.astype(jnp.float32),
                depth_enc.astype(jnp.float32))
    if wire != "qpack8":
        raise ValueError(f"unknown wire format {wire!r}; have {WIRE_FORMATS}")

    near, far = _bcast_scale(scale, depth_enc.ndim)
    span = jnp.maximum(far - near, 0.0)
    depth = jnp.where(depth_enc == 0xFFFF, jnp.inf,
                      near + depth_enc.astype(jnp.float32) / 65534.0 * span)
    return _unpack_rgba(image_enc), depth


# -------------------------------------------------------- host-side (numpy)

def qpack8_quantize_np(color: np.ndarray, depth: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Numpy twin of the qpack8 VDI encode, for the host hop (vdi_io /
    VDIPublisher pre-codec pass). color f32[K, 4, H, W],
    depth f32[K, 2, H, W] → (color u32[K, H, W], depth u16[K, H, W],
    near, far). Bit-identical codes to the device encode."""
    color = np.asarray(color, np.float32)
    depth = np.asarray(depth, np.float32)
    finite = np.isfinite(depth)
    if finite.any():
        near = float(depth[finite].min())
        far = float(depth[finite].max())
    else:
        near, far = 0.0, 1.0
    span = far - near
    enc_span = span if span > 0 else 1.0
    with np.errstate(invalid="ignore"):
        q = np.clip(np.round((depth - np.float32(near))
                             / np.float32(enc_span) * _QMAX), 0.0,
                    float(_QMAX))
    q = np.where(finite, q, float(_SENTINEL)).astype(np.uint16)
    d16 = ((q[..., 0, :, :] << np.uint16(8)) | q[..., 1, :, :])
    c8 = np.round(np.clip(color, 0.0, 1.0) * 255.0).astype(np.uint32)
    c32 = (c8[..., 0, :, :] | (c8[..., 1, :, :] << np.uint32(8))
           | (c8[..., 2, :, :] << np.uint32(16))
           | (c8[..., 3, :, :] << np.uint32(24)))
    return c32, d16.astype(np.uint16), near, far


def qpack8_dequantize_np(color_enc: np.ndarray, depth_enc: np.ndarray,
                         near: float, far: float
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`qpack8_quantize_np` → f32 (color [K, 4, H, W],
    depth [K, 2, H, W])."""
    color_enc = np.asarray(color_enc, np.uint32)
    depth_enc = np.asarray(depth_enc, np.uint16)
    span = max(float(far) - float(near), 0.0)
    qs = (depth_enc >> np.uint16(8)).astype(np.float32)
    qe = (depth_enc & np.uint16(0xFF)).astype(np.float32)
    ds = np.where((depth_enc >> np.uint16(8)) == _SENTINEL, np.inf,
                  np.float32(near) + qs / _QMAX * np.float32(span))
    de = np.where((depth_enc & np.uint16(0xFF)) == _SENTINEL, np.inf,
                  np.float32(near) + qe / _QMAX * np.float32(span))
    depth = np.stack([ds, de], axis=-3).astype(np.float32)
    color = np.stack([(color_enc >> np.uint32(s)) & np.uint32(0xFF)
                      for s in (0, 8, 16, 24)],
                     axis=-3).astype(np.float32) / 255.0
    return color, depth
