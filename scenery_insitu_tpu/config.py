"""Unified configuration system.

The reference scattered configuration across three tiers — JVM system
properties (``-DVolumeBenchmark.*``), fields poked in by C++ through JNI
before init, and hardcoded Kotlin vals / shader ``#define`` feature flags
(SURVEY.md §5 "Config / flag system"; reference DistributedVolumes.kt:88-131,
VolumeFromFileExample.kt:69-82). Here everything lives in one tree of frozen
dataclasses, overridable from environment variables, a JSON file, or
``key.path=value`` strings, in that precedence order.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

ENV_PREFIX = "SITPU_"

# The benched in-plane occupancy tile count (docs/PERF.md "Empty-space
# skipping") — the ONE place the default lives: slicer.make_spec's auto
# resolution (occupancy_vtiles == -1 on TPU),
# models.pipelines.resolve_occupancy_cfg's pyramid/sim modes, and
# occupancy.default_bricks' y-brick cap all read it, so re-benching the
# default can never leave the sites disagreeing.
OCCUPANCY_VTILES_DEFAULT = 16


@dataclass(frozen=True)
class RenderConfig:
    """Plain raycast / framebuffer settings (≅ VolumeRaycaster.comp knobs)."""

    width: int = 1280
    height: int = 720
    max_steps: int = 512           # samples along each ray
    step_scale: float = 1.0        # multiplies the nominal 1-voxel step
    gamma: float = 2.2             # display gamma applied at host boundary
    background: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    early_exit_alpha: float = 0.999  # ≅ AccumulatePlainImage.comp early exit
    # Ambient occlusion (off by default, like the reference's inactive
    # scaffolding ComputeRaycast.comp:147-191): 0 disables; > 0 darkens
    # samples by the blurred-opacity occlusion field (ops/ao.py).
    ao_strength: float = 0.0
    ao_radius: int = 4               # occlusion neighborhood radius, voxels


@dataclass(frozen=True)
class VDIConfig:
    """Supersegment (VDI) generation settings (≅ VDIGenerator.comp knobs)."""

    max_supersegments: int = 20     # K; reference default 20 (DistributedVolumes.kt:99)
    # Fixed color-difference threshold for closing a supersegment. The
    # reference adaptively binary-searches a per-pixel threshold so each ray
    # emits between K*(1-delta) and K segments (VDIGenerator.comp:380-529);
    # adaptive=True enables the same behavior via a bounded search.
    threshold: float = 0.0
    adaptive: bool = True
    adaptive_iters: int = 6         # binary search iterations when adaptive
    adaptive_delta: float = 0.15    # accept counts in [K*(1-delta), K]
    # "search": adaptive_iters counting marches (binary search).
    # "histogram": ONE counting march evaluating histogram_bins candidate
    # thresholds simultaneously (possible because the break metric compares
    # consecutive items — see ops/supersegments.py) then pick per pixel.
    # "temporal": NO counting march — the per-pixel threshold is carried
    # across frames and nudged by a feedback controller from the true
    # segment count observed during the write march itself (see
    # slicer.generate_vdi_mxu_temporal). One march per frame; exploits the
    # frame-to-frame coherence of an in-situ loop.
    adaptive_mode: str = "search"
    histogram_bins: int = 16
    # temporal mode: per-frame outward decay of the controller's bisection
    # bracket (1.0 = frozen bracket, never re-adapts; smaller = tracks
    # faster-changing scenes at the cost of steady-state wobble), and the
    # clamp range the controller moves inside (thr_max matches the
    # histogram candidate ceiling, ss.threshold_candidates).
    temporal_track: float = 0.9
    thr_min: float = 1e-3
    thr_max: float = 2.0

    def __post_init__(self):
        if self.adaptive_mode not in ("search", "histogram", "temporal"):
            raise ValueError(
                f"adaptive_mode must be 'search', 'histogram' or "
                f"'temporal', got {self.adaptive_mode!r}")
    # Occupancy grid (≅ OctreeCells r32ui [W/8, H/8, K]): cell size in pixels.
    occupancy_cell: int = 8


@dataclass(frozen=True)
class SliceMarchConfig:
    """MXU slice-march raycaster settings (ops/slicer.py — the TPU-native
    engine; the gather-path raycaster in ops/raycast.py is the portable
    reference implementation)."""

    # Render engine: "mxu" = slice march (fast on TPU), "gather" = per-ray
    # trilinear gathers (reference path), "auto" = mxu on TPU else gather
    # (resolved by ops.slicer.resolve_engine; consumed by the pipelines'
    # `engine=` argument and the session loop).
    engine: str = "auto"
    # Intermediate grid resolution multiplier over the in-plane voxel count.
    scale: float = 1.25
    # Slices folded per scan step (bounds carry round-trips through HBM).
    chunk: int = 16
    # Resampling matmul operand dtype: "bf16" (MXU-native) or "f32".
    matmul_dtype: str = "bf16"
    # Storage dtype of the MARCHED volume copy: "bf16" halves the volume
    # bytes every march (and the distributed halo-exchange bytes) — the
    # resampling matmuls were casting operands to bf16 anyway
    # (matmul_dtype) and all accumulation stays f32, so the render-side
    # precision loss is one storage rounding of the field. The SIM state
    # is never touched (its ~1e-3 per-step increments need f32 — see
    # models/pipelines.py). "f32" = render the sim field as-is.
    render_dtype: str = "f32"
    # Minimum eye-depth ratio; slices closer to the eye plane are dropped.
    s_floor: float = 1e-3
    # Empty-space skipping: skip slice chunks whose value range maps to
    # zero alpha (≅ the reference's OctreeCells occupancy acceleration,
    # VDIGenerator.comp:232-254 — here consumed, per-frame, by the march).
    skip_empty: bool = True
    # In-plane occupancy tiles: 0 = chunk-granular skipping only; N > 0
    # also splits each slice plane into N row tiles and skips the
    # resampling matmuls + TF for output row blocks whose support is
    # provably empty (see slicer.AxisSpec.vtiles). Adds N lax.cond
    # branches per chunk — worth it on sparse fields, overhead on dense.
    # -1 (the default) resolves per backend in slicer.make_spec: 16 on
    # TPU (the benched winner on sparse Gray-Scott — see
    # benchmarks/occupancy_bench.py and docs/PERF.md "Empty-space
    # skipping"), 0 elsewhere (the branches are pure overhead on CPU).
    # A request larger than the geometry supports is clamped and the
    # reduction recorded on the fallback ledger (occupancy.vtiles_clamp).
    occupancy_vtiles: int = -1
    # Supersegment-fold schedule for the VDI marches:
    #   "xla"        sequential ss.push machine in a lax.scan (every slice
    #                round-trips the [K] state through HBM — the portable
    #                reference schedule, fastest on CPU);
    #   "pallas"     round-3 two-phase machine kernel (ops/pallas_march.py);
    #   "seg"        round-4 segmented-scan fold (ops/seg_fold.py): start
    #                flags / segment ids / transmittance all data-parallel,
    #                [K] state touched once per chunk;
    #   "pallas_seg" the seg fold's VMEM pixel-strip twin (ops/pallas_seg.py);
    #   "pallas_fused" shade-in-kernel: the TF + opacity correction +
    #                depth streams move into the fold kernel (≅ the
    #                reference's single-kernel generation,
    #                VDIGenerator.comp + AccumulateVDI.comp);
    #   "fused_stream" the whole-march fused fold: chunk loop inside the
    #                kernel grid, [K] state VMEM-resident per pixel strip
    #                (one HBM round trip per march; costs a f32[S,Nj,Ni]
    #                stream buffer);
    #   "auto"       pallas_seg on TPU (compile-probe gated, falling back
    #                to seg), xla elsewhere.
    fold: str = "auto"

    def __post_init__(self):
        if self.matmul_dtype not in ("bf16", "f32"):
            raise ValueError(f"matmul_dtype must be 'bf16' or 'f32', "
                             f"got {self.matmul_dtype!r}")
        if self.render_dtype not in ("bf16", "f32"):
            raise ValueError(f"render_dtype must be 'bf16' or 'f32', "
                             f"got {self.render_dtype!r}")


@dataclass(frozen=True)
class CompositeConfig:
    """Sort-last VDI compositing (≅ VDICompositor.comp)."""

    max_output_supersegments: int = 20
    # Re-segmentation threshold search on the composited ray (same meaning as
    # VDIConfig.threshold/adaptive).
    adaptive: bool = True
    adaptive_iters: int = 6
    # Merge-fold schedule: "xla" = lax.scan over slots; "pallas" = fused
    # pixel-tile kernel (ops.pallas_composite); "auto" = pallas on TPU.
    backend: str = "auto"
    # Sort-last exchange schedule (docs/PERF.md "Exchange modes"):
    #   "all_to_all"  one blocking lax.all_to_all of all column fragments,
    #                 then an N·K-wide sort-merge per pixel (≅ the
    #                 reference's distributeVDIs MPI all-to-all shape);
    #   "ring"        n-1 lax.ppermute hops around the ICI ring, each
    #                 incoming K-fragment merged into a per-rank sorted
    #                 accumulator by the pairwise ordered merge
    #                 (ops.composite.merge_vdis_pairwise) — no N·K bitonic
    #                 sort, and XLA overlaps the next hop with the current
    #                 merge. Single-rank meshes fall back to all_to_all
    #                 (both are the identity there).
    exchange: str = "all_to_all"
    # Ring accumulator cap, in supersegment slots per pixel. 0 = lossless:
    # the accumulator grows to N·K slots and ring output matches the
    # all_to_all path exactly. > 0 bounds the live per-pixel working set
    # to ring_slots + K slots (e.g. 2K at ring_slots=K) by dropping the
    # FARTHEST segments of overfull pixels at every merge — bounded
    # memory, approximate on pixels that overflow the cap.
    ring_slots: int = 0
    # Supersegment wire format of the sort-last exchange (docs/PERF.md
    # "Wire formats"; ops/wire.py):
    #   "f32"     6 f32 lanes, 24 B/slot — bit-exact, the pre-wire path;
    #   "bf16"    color+depth cast to bfloat16, 12 B/slot (2×), lossy;
    #   "qpack8"  RGBA → u8 unorm in a u32 lane + the depth pair → u8
    #             each (per-fragment [near, far] normalization, sentinel
    #             0xFFFF round-trips +inf empty slots exactly) in a u16
    #             lane, 6 B/slot (4×), lossy.
    # Encode runs before the collective and decode after it in BOTH
    # exchange schedules, so ICI bytes shrink either way; the composite
    # itself always runs in f32. Quantized modes are lossy by contract
    # (tests hold them to PSNR floors).
    wire: str = "f32"
    # Frame schedule (docs/PERF.md "Tile waves"):
    #   "frame"  the whole frame is one march → one exchange → one
    #            composite (the monolithic SPMD chain — exchange time
    #            adds serially to march time);
    #   "waves"  the column block (tile) is the unit of march, exchange,
    #            composite and delivery: each rank marches one
    #            column-block wave at a time and, while wave w+1
    #            marches, wave w's fragments circulate and fold
    #            (software-pipelined lax.scan with a double-buffered
    #            fragment slot — XLA overlaps the collective with the
    #            next wave's march inside one compiled step). Lossless
    #            waves are parity-exact with the frame schedule; the
    #            session can deliver finished column blocks before the
    #            frame closes. Single-rank meshes degrade to "frame"
    #            (ledgered) — there is nothing to overlap.
    schedule: str = "frame"
    # Column-block waves per rank-owned block under schedule="waves"
    # (the frame is n_ranks * wave_tiles tiles). The intermediate width
    # must divide by ranks * wave_tiles. More waves = finer overlap and
    # lower tile-delivery latency, but each wave re-reads the volume's
    # live chunks (march state is per-wave) — 2-8 is the useful range.
    wave_tiles: int = 4
    # Per-rank supersegment budget of the sort-last fold (docs/PERF.md
    # "Empty-space skipping"):
    #   "static"     every rank's adaptive threshold targets the full K
    #                (the pre-ISSUE-6 behavior, bit-exact);
    #   "occupancy"  rank r targets its share of the mesh-wide budget
    #                N*K, proportional to its occupancy-pyramid live
    #                fraction and clamped to [k_budget_min, K]
    #                (ops/occupancy.k_budget_target). Array SHAPES stay
    #                at K on every rank (one SPMD program): sparse slabs
    #                emit coarser VDIs whose unused slots stay +inf
    #                (near-free on a quantized wire), dense slabs keep
    #                full fidelity — a quality/work re-balance, not a
    #                memory one.
    k_budget: str = "static"
    k_budget_min: int = 4      # floor of the occupancy budget, slots
    # Render rebalancing (docs/PERF.md "Render rebalancing"): the SIM
    # sharding always stays the even 1-D z-slab (halo exchange, sim
    # state untouched), but the RENDER decomposition can differ:
    #   "even"       rank r marches slab [r*D/n, (r+1)*D/n) — the
    #                pre-ISSUE-10 decomposition (note: the gather
    #                engine's SAMPLE LADDER now derives from the global
    #                box under every mode, matching single-device
    #                sample positions — docs/PERF.md "Render
    #                rebalancing"; the MXU engine always marched the
    #                global slice ladder and is bit-exact vs pre-10);
    #   "occupancy"  rank r marches a PLANNED contiguous z-slice band
    #                (ops/occupancy.slice_plan — greedy prefix-sum
    #                equalization of the occupancy pyramid's per-z live
    #                work), materialized from the even shards by
    #                parallel/mesh.reslab_z with the same seam-exact
    #                1-voxel halo contract as halo_exchange_z. Bands pad
    #                to the plan's max depth (static SPMD shapes; padded
    #                slices are masked and the pyramid admits zero for
    #                them, so skipping eats the padding). The plan is
    #                computed host-side between frames from fetched live
    #                fractions; a plan CHANGE recompiles the step — the
    #                quantum + hysteresis below bound how often.
    #   "bricks"     the render decomposition is a NON-CONVEX brick map
    #                (parallel/bricks.BrickMap; docs/SCENARIOS.md): the
    #                global z extent splits into rebalance_bricks equal
    #                bricks and the session re-plans by brick-STEALING —
    #                greedy per-brick live-work equalization moving at
    #                most rebalance_max_moves bricks per replan
    #                (parallel.bricks.steal_plan). Each rank marches its
    #                brick set through per-brick ownership intervals;
    #                the sort-last composite is invariant to which rank
    #                owns which brick (tests/test_bricks.py), and the
    #                even-convex map short-circuits bitwise to the
    #                pre-brick path.
    rebalance: str = "even"
    # Temporal fragment reuse (docs/PERF.md "Temporal deltas"):
    #   "off"     every frame re-marches every rank (the pre-ISSUE-12
    #             behavior — the off path inserts zero ops);
    #   "ranges"  each rank carries its previous marched VDI fragment
    #             plus a dirty signature — the occupancy pyramid's
    #             per-cell [lo, hi] value ranges (already computed every
    #             frame, PR 6) concatenated with the camera pose — and
    #             SKIPS the march (lax.cond; the matmul waves never
    #             issue) when the signature moved by at most
    #             delta.range_tol and the camera is bit-unchanged. The
    #             exchange + composite still run every frame (other
    #             ranks may be dirty). MXU VDI steps only; the gather /
    #             hybrid / plain builders ledger the knob inert
    #             (delta.reuse). range_tol = 0 with a static camera is
    #             bit-exact vs recompute; a field change that preserves
    #             every per-brick [lo, hi] exactly is invisible to the
    #             detector — the documented contract of a range-based
    #             dirty predicate.
    temporal_reuse: str = "off"
    # Frames between host-side re-plans under rebalance="occupancy"
    # (runtime/session.py fetches the z live profile and re-plans every
    # this many frames; each ADOPTED plan recompiles the step).
    rebalance_period: int = 8
    # Plan stability: a fresh plan is adopted only when some band
    # boundary moves by more than this fraction of the even slab depth
    # (D/n) — below it the previous plan is kept and nothing recompiles.
    rebalance_hysteresis: float = 0.25
    # Floor on any rank's planned band depth, slices. Must cover the
    # deepest halo the step needs (1 for trilinear seams; ao_radius + 1
    # for AO pre-shading) — parallel/mesh.reslab_z validates this and
    # names the offending rank.
    rebalance_min_depth: int = 4
    # Band boundaries snap to multiples of this many slices — coarser
    # quanta mean fewer distinct plans, fewer recompiles.
    rebalance_quantum: int = 4
    # rebalance="bricks": brick count of the regular z brick grid. 0 =
    # auto (parallel.bricks.auto_nbricks: the largest divisor of the
    # depth at most 4 * n_ranks — fine enough to steal by, coarse
    # enough that per-brick march overhead stays small).
    rebalance_bricks: int = 0
    # rebalance="bricks": bricks allowed to change owner per replan.
    # Caps both the recompile delta and the extra reslab routing one
    # replan can introduce (each move is one more distinct shard offset
    # the ppermute rotation set may need).
    rebalance_max_moves: int = 2

    def __post_init__(self):
        if self.exchange not in ("all_to_all", "ring"):
            raise ValueError(f"exchange must be 'all_to_all' or 'ring', "
                             f"got {self.exchange!r}")
        if self.ring_slots < 0:
            raise ValueError(f"ring_slots must be >= 0 (0 = lossless), "
                             f"got {self.ring_slots}")
        if self.wire not in ("f32", "bf16", "qpack8"):
            raise ValueError(f"wire must be 'f32', 'bf16' or 'qpack8', "
                             f"got {self.wire!r}")
        if self.schedule not in ("frame", "waves"):
            raise ValueError(f"schedule must be 'frame' or 'waves', "
                             f"got {self.schedule!r}")
        if self.wave_tiles < 1:
            raise ValueError(f"wave_tiles must be >= 1, "
                             f"got {self.wave_tiles}")
        if self.k_budget not in ("static", "occupancy"):
            raise ValueError(f"k_budget must be 'static' or 'occupancy', "
                             f"got {self.k_budget!r}")
        if self.k_budget_min < 1:
            raise ValueError(f"k_budget_min must be >= 1, "
                             f"got {self.k_budget_min}")
        if self.rebalance not in ("even", "occupancy", "bricks"):
            raise ValueError(f"rebalance must be 'even', 'occupancy' or "
                             f"'bricks', got {self.rebalance!r}")
        if self.temporal_reuse not in ("off", "ranges"):
            raise ValueError(f"temporal_reuse must be 'off' or 'ranges', "
                             f"got {self.temporal_reuse!r}")
        if self.rebalance_period < 1:
            raise ValueError(f"rebalance_period must be >= 1, "
                             f"got {self.rebalance_period}")
        if self.rebalance_hysteresis < 0.0:
            raise ValueError(f"rebalance_hysteresis must be >= 0, "
                             f"got {self.rebalance_hysteresis}")
        if self.rebalance_min_depth < 1:
            raise ValueError(f"rebalance_min_depth must be >= 1, "
                             f"got {self.rebalance_min_depth}")
        if self.rebalance_quantum < 1:
            raise ValueError(f"rebalance_quantum must be >= 1, "
                             f"got {self.rebalance_quantum}")
        if self.rebalance_bricks < 0:
            raise ValueError(f"rebalance_bricks must be >= 0 (0 = auto), "
                             f"got {self.rebalance_bricks}")
        if self.rebalance_max_moves < 1:
            raise ValueError(f"rebalance_max_moves must be >= 1, "
                             f"got {self.rebalance_max_moves}")


@dataclass(frozen=True)
class LODConfig:
    """Multi-resolution brick marching (docs/PERF.md "LOD marching";
    docs/SCENARIOS.md "LOD levels").

    Rides the brick render decomposition (``composite.rebalance ==
    "bricks"``): each brick of the map carries a refinement ``level``
    (parallel/bricks.BrickMap.level) chosen host-side at every replan
    (parallel/lod.py) from the occupancy profile, a conservative
    screen-space error bound from the camera, and the transfer-function
    straddle gate. A level-``l`` brick marches a ``2^l``-downsampled
    copy (average-pooled on device at materialization,
    parallel/mesh.reslab_bricks_lod) through the same `slice_march`
    machinery at ``step_scale = 2^-l``; its supersegments composite
    unchanged. An all-level-0 map is BITWISE the pre-LOD brick path.
    Enabled without a brick map, the knob is inert and ledgered
    (lod.inert). The MXU VDI march is the only coarse consumer — the
    gather engine samples fine and ledgers the map's levels inert
    (lod.engine)."""

    # Master switch: select per-brick refinement levels at every brick
    # replan. False = every brick stays level 0 (the flat PR-15 map).
    enabled: bool = False
    # Deepest refinement level a brick may coarsen to (downsample factor
    # 2^max_level). The planner additionally caps levels so 2^l divides
    # the brick depth and both in-plane extents.
    max_level: int = 2
    # Screen-space error budget, intermediate-grid pixels: a brick may
    # coarsen to level l only while its projected coarse-voxel footprint
    # 2^l * voxel * focal_px / eye_distance stays at or below this.
    error_px: float = 1.0
    # Coarsen provably-empty bricks (occupancy live fraction at or below
    # live_eps) to the admissible cap regardless of the screen bound —
    # air is marched at the coarsest resolution the geometry allows.
    coarsen_empty: bool = True
    live_eps: float = 1e-3
    # Opacity-edge sensitivity of the TF-straddle gate: an alpha knot
    # with |slope delta| > tf_edge_eps strictly inside a brick's value
    # range pins that brick at level 0 (never coarsened — downsampling
    # across a TF edge aliases).
    tf_edge_eps: float = 1e-4
    # Coarsening deadband: a brick coarsens (level increases, one level
    # per replan) only when the coarser footprint also clears
    # error_px * (1 - hysteresis) — refinement is immediate, coarsening
    # is damped so a camera at the threshold cannot oscillate the level
    # tuple (each adopted tuple recompiles the step).
    hysteresis: float = 0.2

    def __post_init__(self):
        if not 0 <= self.max_level <= 8:
            raise ValueError(f"max_level must be in [0, 8], "
                             f"got {self.max_level}")
        if self.error_px <= 0.0:
            raise ValueError(f"error_px must be > 0, "
                             f"got {self.error_px}")
        if self.live_eps < 0.0:
            raise ValueError(f"live_eps must be >= 0, "
                             f"got {self.live_eps}")
        if self.tf_edge_eps < 0.0:
            raise ValueError(f"tf_edge_eps must be >= 0, "
                             f"got {self.tf_edge_eps}")
        if not 0.0 <= self.hysteresis < 1.0:
            raise ValueError(f"hysteresis must be in [0, 1), "
                             f"got {self.hysteresis}")


@dataclass(frozen=True)
class TopologyConfig:
    """Mesh topology — the scale-out plane (docs/MULTIHOST.md).

    Every collective in the single-domain pipeline assumes one flat ICI
    domain. This block makes the ICI/DCN split first-class: ``num_hosts``
    ICI domains ("hosts" — one per pod slice / node) of ``domain_size``
    devices each. With ``num_hosts > 1`` the compositing mesh becomes a
    2-D ``(hosts, ranks)`` mesh (parallel/topology.py) and the sort-last
    composite runs in TWO levels: intra-domain ring/waves over ICI
    exactly as today, then an inter-domain exchange of already-partially-
    composited column blocks over DCN (parallel/hier.py), resegmented
    ONCE so a hierarchical frame matches the flat composite
    (tests/test_topology.py). ``num_hosts == 1`` (the default) is
    BITWISE the flat single-level path."""

    # Devices per ICI domain. 0 = auto: all devices / num_hosts (the
    # device count must split evenly — parallel/topology.py validates).
    domain_size: int = 0
    # ICI domains (hosts). 1 = the flat single-domain path, bitwise
    # identical to the pre-topology pipeline.
    num_hosts: int = 1
    # Mesh axis name of the inter-domain (DCN) axis; the intra-domain
    # axis reuses MeshConfig.axis_name ("ranks").
    hosts_axis: str = "hosts"
    # Wire format of the inter-domain (DCN) hop (docs/PERF.md "Wire
    # formats" — same codec family as CompositeConfig.wire, applied to
    # the partially-composited column blocks that cross DCN): "f32" is
    # bit-exact (the parity contract); "qpack8" is the recommended
    # production setting on bandwidth-starved DCN (4x fewer bytes, PSNR
    # floors tested). The intra-domain ICI hop keeps composite.wire.
    dcn_wire: str = "f32"

    def __post_init__(self):
        if self.domain_size < 0:
            raise ValueError(f"domain_size must be >= 0 (0 = auto), "
                             f"got {self.domain_size}")
        if self.num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, "
                             f"got {self.num_hosts}")
        if not self.hosts_axis:
            raise ValueError("hosts_axis must be a non-empty axis name")
        if self.dcn_wire not in ("f32", "bf16", "qpack8"):
            raise ValueError(f"dcn_wire must be 'f32', 'bf16' or "
                             f"'qpack8', got {self.dcn_wire!r}")


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh / parallelism settings (replaces rank/commSize fields the
    reference received from C++: DistributedVolumes.kt:103-117).

    Domain decomposition is 1-D over z (the pipeline's halo exchange and
    ownership masks are built for z-slabs); unevenly-sized and multi-grid
    per-rank layouts go through core.scene.MultiGridScene instead of a
    decomposition knob here."""

    # Number of devices participating in sort-last compositing; 0 = all.
    num_devices: int = 0
    axis_name: str = "ranks"


@dataclass(frozen=True)
class SimConfig:
    """Built-in simulation settings (standalone mode; the reference could not
    run standalone — README.md:16 — this framework can)."""

    # gray_scott | vortex | lennard_jones | sho | hybrid (vortex + tracers)
    kind: str = "gray_scott"
    grid: Tuple[int, int, int] = (128, 128, 128)
    steps_per_frame: int = 10
    dt: float = 1.0
    # Gray-Scott parameters ("lambda" regime — stable labyrinths in 3D;
    # the classic 2D soliton params 0.0545/0.062 die out in 3D)
    gs_f: float = 0.037
    gs_k: float = 0.060
    gs_du: float = 0.16
    gs_dv: float = 0.08
    num_particles: int = 100_000
    # Sphere radius for the particle/hybrid render paths: world units for
    # lennard_jones/sho, voxel units for hybrid tracers.
    particle_radius: float = 0.35
    # Advance gray_scott through the time-fused Pallas stencil on TPU
    # (sim/pallas_stencil.py — T steps per volume round trip instead of
    # one; probe-gated, degrades to the XLA roll path off-TPU or when no
    # schedule compiles). False pins the XLA roll formulation — the
    # sim-fusion lever's A/B switch.
    fused_stencil: bool = True


@dataclass(frozen=True)
class RuntimeConfig:
    """Session-loop, dump and benchmark flags (≅ the hardcoded vals
    generateVDIs/saveFinal/benchmarking, DistributedVolumes.kt:88-92)."""

    generate_vdis: bool = True
    save_final: bool = False
    dump_dir: str = "dumps"
    benchmark: bool = False
    benchmark_frames: int = 100
    stats_window: int = 100         # frames between timer-stat dumps
    dataset: str = "procedural"
    # Frames rolled into ONE lax.scan-based executable per launch (0/1 =
    # eager per-frame dispatch). Amortizes the per-launch dispatch tax
    # (docs/PERF.md H2) at the cost of steering/camera latency: steering
    # drains and regime changes only take effect at block boundaries.
    # Applies to volume-sim VDI sessions; other modes fall back to the
    # eager loop (runtime/session.py logs the downgrade).
    scan_frames: int = 0
    # Device->host pipeline depth of the eager loop (docs/PERF.md "Async
    # delivery"): how many dispatched frames may have their host copies
    # in flight before the loop blocks on the oldest. 1 = the historical
    # one-deep overlap (bitwise the pre-async behavior); deeper values
    # only help when host delivery is slower than device compute AND the
    # background delivery executor is absorbing the payloads — each
    # extra slot pins roughly one more frame of host-copy memory.
    pipeline_depth: int = 1

    def __post_init__(self):
        if self.pipeline_depth < 1:
            raise ValueError(f"runtime.pipeline_depth must be >= 1, "
                             f"got {self.pipeline_depth}")


@dataclass(frozen=True)
class ObsConfig:
    """Observability / telemetry (scenery_insitu_tpu/obs — structured
    spans, device counters, the fallback ledger; docs/OBSERVABILITY.md).

    Disabled (the default) the recorder is a no-op shell around the
    per-phase Timers: no events are recorded and no files are written —
    the PR-1 hot path. Enabled, every session phase becomes a structured
    span (frame/rank attribution) and ``Session.run`` flushes the
    configured sinks at the end of the loop."""

    enabled: bool = False
    # Chrome-trace / Perfetto JSON ("" = don't write). Open the file at
    # ui.perfetto.dev; complements the device-side profiler dir that
    # ``Session.run(profile_dir=...)`` captures.
    trace_path: str = ""
    # JSONL event stream + final summary line ("" = don't write).
    metrics_path: str = ""
    # Timer window for the embedded Timers (0 = runtime.stats_window).
    window: int = 0
    # Fleet telemetry side-channel (obs/collector.py, docs/
    # OBSERVABILITY.md "Fleet tracing"): the Collector's event SUB
    # endpoint to PUB batched obs events/counters/ledger deltas to
    # ("" = no side-channel). Loss-tolerant by construction: every send
    # is non-blocking, a dead or slow collector costs drops (ledgered
    # `obs.collector`), never a stalled render loop.
    collector: str = ""
    # The Collector's heartbeat ROUTER endpoint ("" = no clock-offset
    # pings; batches then align on wall clocks alone).
    collector_hb: str = ""
    # Seconds between telemetry batch publishes (and heartbeat pings)
    # on the session's frame loop.
    collector_interval_s: float = 0.25

    def __post_init__(self):
        if self.collector_interval_s <= 0:
            raise ValueError(f"collector_interval_s must be > 0, "
                             f"got {self.collector_interval_s}")


@dataclass(frozen=True)
class SLOConfig:
    """Live service-level objectives (obs/slo.py, docs/OBSERVABILITY.md
    "SLO engine"): rolling-window p50/p99 estimators over frame latency,
    serve staleness and camera-to-pixel latency, checked ON the run.

    A budget of 0 disables that gate (the estimator still tracks the
    metric for ``snapshot()``). A breach mints a typed ``slo_breach``
    event, bumps the ``slo_breaches`` counter and lands one deduped
    ``slo.breach`` ledger row — machine-readable health for the relay
    tree's autoscale signal (ROADMAP item 2) and the elastic fleet's
    frames-to-recover gate (item 5)."""

    enabled: bool = False
    # Rolling window, in samples per metric (p50/p99 are computed over
    # at most this many most-recent observations — O(window) memory).
    window: int = 128
    # Breach checks need at least this many samples first (a p99 of 3
    # frames is noise, not a signal).
    min_samples: int = 16
    # End-to-end frame latency budget, ms (sim -> delivered payload,
    # the session's per-frame wall clock). 0 = no gate.
    frame_p99_ms: float = 0.0
    # Serve staleness budget: answers rendered from a VDI more than
    # this many frames behind the stream head breach. 0 = no gate.
    staleness_p99_frames: float = 0.0
    # Camera-to-pixel budget, ms (camera request received -> answer
    # bytes handed to the socket, measured on the serve tier). 0 = no
    # gate.
    camera_to_pixel_p99_ms: float = 0.0
    # Per-phase budget, ms, applied to every recorded session phase
    # span (sim/dispatch/fetch/sinks...). 0 = no gate.
    phase_p99_ms: float = 0.0
    # Delivery lag budget, ms: dispatch-to-delivered latency of a frame
    # through the async delivery executor (runtime/delivery.py,
    # docs/PERF.md "Async delivery") — how far behind the render loop
    # the background sink tier is running. 0 = no gate.
    delivery_lag_p99_ms: float = 0.0

    def __post_init__(self):
        if self.window < 8:
            raise ValueError(f"slo.window must be >= 8, got {self.window}")
        if self.min_samples < 1 or self.min_samples > self.window:
            raise ValueError(f"need 1 <= min_samples <= window, got "
                             f"{self.min_samples} (window {self.window})")
        for k in ("frame_p99_ms", "staleness_p99_frames",
                  "camera_to_pixel_p99_ms", "phase_p99_ms",
                  "delivery_lag_p99_ms"):
            if getattr(self, k) < 0:
                raise ValueError(f"slo.{k} must be >= 0 (0 = no gate), "
                                 f"got {getattr(self, k)}")


@dataclass(frozen=True)
class FaultConfig:
    """Self-healing delivery-plane knobs (docs/ROBUSTNESS.md): liveness
    deadlines, reconnect backoff, sink quarantine and the tile-frame
    assembler window. Every seam where bytes cross a failure domain
    (zmq VDI/steering streams, the UDP video stream, the shm ingest
    ring, in-process sinks) reads its tolerance from here."""

    # Publishers emit a lightweight heartbeat when idle this long, so
    # subscribers can tell "no frames" from "dead peer"
    # (VDIPublisher.maybe_heartbeat / SteeringPublisher.heartbeat).
    heartbeat_period_s: float = 2.0
    # A subscriber/endpoint that has seen NO traffic (frames, tiles or
    # heartbeats) for this long considers the peer lost and reconnects
    # with bounded exponential backoff (utils/retry.py). <= 0 disables
    # liveness supervision.
    liveness_timeout_s: float = 10.0
    # Reconnect backoff ladder: base * 2**attempt seconds, capped.
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    # A frame/tile sink or on_steer callback failing this many
    # CONSECUTIVE times is quarantined (disabled + `session.sink`
    # ledger) instead of killing the render loop; a success in between
    # resets the count (runtime/failsafe.SinkGuard).
    max_sink_failures: int = 3
    # FrameAssembler: an incomplete tile frame is abandoned (ledgered
    # `stream.gap`) once this many NEWER frames have started — the
    # `VideoReceiver._parts` eviction pattern, generalized.
    assembler_window: int = 4
    # Steering messages larger than this are dropped before unpack (the
    # steering socket is network-facing; a hostile/buggy viewer must
    # not be able to balloon the renderer).
    max_message_bytes: int = 1 << 20

    def __post_init__(self):
        if self.heartbeat_period_s <= 0:
            raise ValueError(f"heartbeat_period_s must be > 0, "
                             f"got {self.heartbeat_period_s}")
        if self.backoff_base_s <= 0 or self.backoff_cap_s < self.backoff_base_s:
            raise ValueError(
                f"need 0 < backoff_base_s <= backoff_cap_s, got "
                f"{self.backoff_base_s}, {self.backoff_cap_s}")
        if self.max_sink_failures < 1:
            raise ValueError(f"max_sink_failures must be >= 1, "
                             f"got {self.max_sink_failures}")
        if self.assembler_window < 1:
            raise ValueError(f"assembler_window must be >= 1, "
                             f"got {self.assembler_window}")
        if self.max_message_bytes < 1024:
            raise ValueError(f"max_message_bytes must be >= 1024, "
                             f"got {self.max_message_bytes}")


@dataclass(frozen=True)
class DeltaConfig:
    """Temporal-delta plane (docs/PERF.md "Temporal deltas"): steady
    frames cost bytes and FLOPs proportional to what changed.

    ``enabled`` turns on the P-frame WIRE codec on `VDIPublisher`
    (requires ``precision="qpack8"`` — the monotone quantizer is what
    makes code-space comparison exact): per published tile the stream
    carries a SKIP record, a sparse changed-slot residual, or a full
    I-tile, and subscribers reconstruct bit-exactly (ops/delta.py).
    The RE-MARCH half is switched separately by
    ``composite.temporal_reuse`` (it changes the compiled step's
    signature); ``range_tol`` is its dirty-detector tolerance."""

    # P-frame wire codec on VDIPublisher/VDISubscriber.
    enabled: bool = False
    # Forced I-tile cadence, frames: a joining subscriber or a stream
    # that dropped a record recovers within one period (the subscriber
    # ledgers the wait as stream.delta_resync). Smaller = faster
    # recovery, more bytes.
    iframe_period: int = 8
    # Dirty-detector tolerance of composite.temporal_reuse = "ranges":
    # a rank re-marches only when some occupancy-range cell moved by
    # more than this (absolute, field units). 0 = exact mode — any
    # range motion re-marches and reuse is bitwise vs recompute.
    range_tol: float = 0.0

    def __post_init__(self):
        if self.iframe_period < 1:
            raise ValueError(f"iframe_period must be >= 1, "
                             f"got {self.iframe_period}")
        if self.range_tol < 0.0:
            raise ValueError(f"range_tol must be >= 0, "
                             f"got {self.range_tol}")


@dataclass(frozen=True)
class DeliveryConfig:
    """Asynchronous delivery plane (runtime/delivery.py, docs/PERF.md
    "Async delivery"): a background worker tier drains the per-frame
    sink work off the render-loop thread, so steady-state frame time is
    max(device, host) instead of device + host.

    Disabled (the default) every sink runs inline on the loop thread —
    bitwise the pre-async behavior. Enabled, the loop enqueues each
    fetched frame's payloads onto a bounded FIFO and a worker thread
    runs the sinks (tile sinks in ascending column order, then frame
    sinks; frames strictly FIFO) behind the same SinkGuard quarantine.
    ``overflow`` decides what a full queue costs: ``block`` (lossless —
    the loop waits, correct for disk/checkpoint sinks) or
    ``drop_oldest`` (latest-wins — the oldest undelivered frame is shed
    with a ``delivery.shed`` ledger row + ``delivery_sheds`` counter,
    correct for live streaming where a stale frame has no value)."""

    # Run frame/tile sinks on the background executor instead of inline.
    enabled: bool = False
    # Bounded frame queue between the loop and the worker: at most this
    # many undelivered frames in flight before ``overflow`` applies.
    queue_frames: int = 4
    # Full-queue policy: "block" (lossless backpressure) or
    # "drop_oldest" (latest-wins shedding, ledgered).
    overflow: str = "block"
    # Per-tile encode fan-out (docs/PERF.md "Async delivery"): tile-sink
    # calls for one frame run across this many threads with the results
    # APPLIED in ascending tile order, so delivered bytes are
    # bit-identical to the serial path. 1 = serial. Also consumed by
    # VDIPublisher's parallel tile encoder.
    encode_workers: int = 1
    # Seconds ``drain()``/teardown waits for the queue to empty before
    # ledgering the abandon (`delivery.drain`). Generous by default —
    # a teardown must not lose committed frames.
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if self.queue_frames < 1:
            raise ValueError(f"delivery.queue_frames must be >= 1, "
                             f"got {self.queue_frames}")
        if self.overflow not in ("block", "drop_oldest"):
            raise ValueError(f"delivery.overflow must be 'block' or "
                             f"'drop_oldest', got {self.overflow!r}")
        if self.encode_workers < 1:
            raise ValueError(f"delivery.encode_workers must be >= 1, "
                             f"got {self.encode_workers}")
        if self.drain_timeout_s <= 0:
            raise ValueError(f"delivery.drain_timeout_s must be > 0, "
                             f"got {self.drain_timeout_s}")


@dataclass(frozen=True)
class ServeConfig:
    """VDI edge-serving tier (scenery_insitu_tpu/serve; docs/SERVING.md):
    a `ViewerServer` subscribes to the composited VDI stream and answers
    N concurrent client cameras per frame from ONE batched device
    dispatch (`ops.vdi_novel.render_vdi_batch`), so sim+march+composite
    stays O(1) while viewer cost scales on this separate, cacheable
    tier. Every shed, stale or degraded answer is minted on the obs
    ledger (serve.* components, docs/OBSERVABILITY.md)."""

    # Client-facing ROUTER endpoint (":0" = ephemeral port for tests)
    # and the upstream composited-VDI stream to subscribe to.
    bind: str = "tcp://*:6657"
    connect: str = "tcp://localhost:6655"
    # Admission control: clients beyond max_viewers get a typed "shed"
    # answer (serve.shed ledger) instead of service; pending camera
    # requests beyond queue_cap shed the same way (requests coalesce
    # latest-wins per client first, so the queue holds at most one
    # request per admitted client).
    max_viewers: int = 64
    queue_cap: int = 64
    # Cameras per render dispatch. A batch of B <= batch_size cameras
    # pads up to the next `buckets` entry (replicating its last camera;
    # padded lanes are discarded), so the jit cache holds at most
    # len(buckets) programs per (tier, regime) — bounded recompiles.
    batch_size: int = 16
    buckets: Tuple[int, ...] = (1, 2, 4, 8, 16)
    # Bounded staleness: answers rendered from a VDI more than this many
    # frames behind the newest frame the stream has STARTED are stamped
    # stale=True in the client protocol (+ serve.stale ledger) — the
    # viewer knows it is looking at the past.
    staleness_frames: int = 4
    # Quality ladder (docs/SERVING.md "Tiers"): "exact" = closed-form
    # render_vdi_exact; "proxy" = the MXU pre-shaded proxy volume, built
    # once per frame and marched per viewer (the amortization winner);
    # "wire" = the proxy render quantized to u8 wire precision (4x fewer
    # bytes per viewer). Clients pick a tier at hello; unknown tiers
    # degrade here (serve.tier ledger).
    default_tier: str = "proxy"
    # Camera-delta cache: a request whose camera moved by at most this
    # (max-abs over every camera leaf) since the client's last answer ON
    # THE SAME VDI FRAME re-serves the cached pixels without rendering.
    cam_tol: float = 1e-6
    # Served image size (fixed per server — per-request sizes would
    # defeat the bounded-recompile contract).
    width: int = 128
    height: int = 96
    # Novel-view plane count. 0 (the default) derives it per adopted
    # frame from the VDI's own deepest finite slab (quantized to 16 so
    # the jit key is stable) — this covers gather-engine VDIs, whose
    # reconstructed plane ladder starts at the camera near plane well
    # before the volume; a fixed count that stops short of the content
    # serves BLANK frames on the proxy tier.
    num_slices: int = 0
    # Intermediate-grid scale of the per-viewer proxy march. The render
    # path's 1.25x oversampling guards a RAW volume's features; the
    # serve proxy is already pre-shaded at the VDI's own resolution, so
    # 1.0 re-renders it without oversampling — ~1.6x cheaper per viewer,
    # which is most of the amortization headroom (docs/SERVING.md).
    march_scale: float = 1.0
    # Clients silent (no request/heartbeat) this long are evicted; their
    # next message re-admits them through admission control.
    client_timeout_s: float = 10.0
    # Liveness-supervise the upstream VDI subscription with fault.*
    # knobs (reconnect + backoff past liveness_timeout_s). Off by
    # default — the PR-11 convention: supervision needs a publisher
    # that pumps heartbeats, or a healthy-but-slow stream gets torn
    # down mid-frame.
    supervise_stream: bool = False

    def __post_init__(self):
        if self.max_viewers < 1:
            raise ValueError(f"max_viewers must be >= 1, "
                             f"got {self.max_viewers}")
        if self.queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {self.queue_cap}")
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, "
                             f"got {self.batch_size}")
        if not self.buckets or list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be a strictly ascending ladder, "
                             f"got {self.buckets}")
        # buckets-vs-batch_size is a CROSS-FIELD constraint: it is
        # checked where the pair is consumed (ViewerServer.__init__),
        # not here — with_overrides applies one assignment at a time,
        # and a per-assignment check would make override order decide
        # whether a valid final config constructs.
        if self.staleness_frames < 0:
            raise ValueError(f"staleness_frames must be >= 0, "
                             f"got {self.staleness_frames}")
        if self.default_tier not in ("exact", "proxy", "wire"):
            raise ValueError(f"default_tier must be 'exact', 'proxy' or "
                             f"'wire', got {self.default_tier!r}")
        if self.cam_tol < 0.0:
            raise ValueError(f"cam_tol must be >= 0, got {self.cam_tol}")
        if self.width < 8 or self.height < 8:
            raise ValueError(f"served size must be >= 8x8, "
                             f"got {self.width}x{self.height}")
        if self.num_slices < 0:
            raise ValueError(f"num_slices must be >= 0 (0 = heuristic), "
                             f"got {self.num_slices}")
        if self.march_scale <= 0.0:
            raise ValueError(f"march_scale must be > 0, "
                             f"got {self.march_scale}")
        if self.client_timeout_s <= 0:
            raise ValueError(f"client_timeout_s must be > 0, "
                             f"got {self.client_timeout_s}")


@dataclass(frozen=True)
class StreamConfig:
    """Steering / streaming endpoints (≅ ZMQ :6655 + UDP :3337,
    VolumeFromFileExample.kt:840-854; DistributedVolumeRenderer.kt:278-283)."""

    steer_bind: str = "tcp://*:6655"
    steer_connect: str = "tcp://localhost:6655"
    video_port: int = 3337
    compress: str = "zstd"          # zstd | zlib | lzma | none (see io.vdi_io)


@dataclass(frozen=True)
class FrameworkConfig:
    render: RenderConfig = field(default_factory=RenderConfig)
    slicer: SliceMarchConfig = field(default_factory=SliceMarchConfig)
    vdi: VDIConfig = field(default_factory=VDIConfig)
    composite: CompositeConfig = field(default_factory=CompositeConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    topology: TopologyConfig = field(default_factory=TopologyConfig)
    sim: SimConfig = field(default_factory=SimConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    stream: StreamConfig = field(default_factory=StreamConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    delta: DeltaConfig = field(default_factory=DeltaConfig)
    delivery: DeliveryConfig = field(default_factory=DeliveryConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    lod: LODConfig = field(default_factory=LODConfig)

    # ------------------------------------------------------------------ IO
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: dict) -> "FrameworkConfig":
        return _merge_into(cls(), d)

    @classmethod
    def from_json_file(cls, path: str) -> "FrameworkConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def with_overrides(self, *assignments: str, **env: Optional[dict]) -> "FrameworkConfig":
        """Apply ``section.key=value`` strings, e.g. ``render.width=512``."""
        cfg = self
        for a in assignments:
            key, _, raw = a.partition("=")
            if not _:
                raise ValueError(f"override must look like section.key=value: {a!r}")
            cfg = _assign(cfg, key.strip().split("."), _parse_value(raw.strip()))
        return cfg

    @classmethod
    def load(cls, path: Optional[str] = None, overrides: Tuple[str, ...] = ()) -> "FrameworkConfig":
        """File < env (SITPU_SECTION_KEY=value) < explicit overrides."""
        cfg = cls.from_json_file(path) if path else cls()
        for name, raw in os.environ.items():
            if not name.startswith(ENV_PREFIX):
                continue
            parts = name[len(ENV_PREFIX):].lower().split("_", 1)
            if len(parts) != 2 or not hasattr(cfg, parts[0]):
                # not a config section: other SITPU_* tooling vars (e.g.
                # SITPU_BENCH_*) share the prefix, so unknown sections
                # cannot be errors — only unknown KEYS of real sections are
                continue
            if tuple(parts) in _REMOVED_KEYS:
                from scenery_insitu_tpu import obs
                obs.degrade("config.removed_key", name, "ignored",
                            _REMOVED_KEYS[tuple(parts)])
                continue
            try:
                cfg = _assign(cfg, parts, _parse_value(raw))
            except (ValueError, AttributeError) as e:
                # a typo'd key/value must not silently do nothing (the
                # reference's three config tiers failed silently too)
                raise ValueError(
                    f"bad config override {name}={raw!r}: {e}") from e
        return cfg.with_overrides(*overrides)


# removed config keys -> deprecation note (accepted-and-warned, not fatal)
_REMOVED_KEYS = {
    ("mesh", "decomposition"): "decomposition is 1-D over z; multi-grid "
                               "layouts go through core.scene.MultiGridScene",
}


def _parse_value(raw: str) -> Any:
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return raw


def _assign(cfg: Any, path: list, value: Any) -> Any:
    head = path[0]
    if not hasattr(cfg, head):
        raise AttributeError(f"no config field {head!r} on {type(cfg).__name__}")
    if len(path) == 1:
        current = getattr(cfg, head)
        if current is not None and not isinstance(value, type(current)):
            if isinstance(current, tuple):
                value = tuple(value)
            elif isinstance(current, float) and isinstance(value, int):
                value = float(value)
            elif isinstance(current, bool) and isinstance(value, str):
                value = value.lower() in ("1", "true", "yes", "on")
            elif isinstance(current, (int, float)) and isinstance(value, str):
                value = type(current)(value)
        return dataclasses.replace(cfg, **{head: value})
    return dataclasses.replace(cfg, **{head: _assign(getattr(cfg, head), path[1:], value)})


def _merge_into(cfg: Any, d: dict) -> Any:
    updates = {}
    for k, v in d.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"no config field {k!r} on {type(cfg).__name__}")
        current = getattr(cfg, k)
        if dataclasses.is_dataclass(current) and isinstance(v, dict):
            updates[k] = _merge_into(current, v)
        elif isinstance(current, tuple) and isinstance(v, list):
            updates[k] = tuple(v)
        else:
            updates[k] = v
    return dataclasses.replace(cfg, **updates)
