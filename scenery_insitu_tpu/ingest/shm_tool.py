"""Shm-channel debug and recovery CLI (≅ the reference's stuck-state
tooling: sem_get.cpp prints a rank's semaphore state, sem_reset.cpp zeroes
it — src/test/cpp/sem_get.cpp, sem_reset.cpp).

Usage:
  python -m scenery_insitu_tpu.ingest.shm_tool NAME           # inspect
  python -m scenery_insitu_tpu.ingest.shm_tool NAME --reset   # clear pins
  python -m scenery_insitu_tpu.ingest.shm_tool NAME --unlink  # remove
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("channel", help="channel name, e.g. /sitpu_vol")
    p.add_argument("--reset", action="store_true",
                   help="clear stale reader pins (crashed-consumer recovery)")
    p.add_argument("--unlink", action="store_true",
                   help="remove the channel from the shm namespace")
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    from scenery_insitu_tpu.ingest import shm

    try:
        stats = shm.channel_stats(args.channel)
    except FileNotFoundError:
        print(f"no channel {args.channel!r}", file=sys.stderr)
        return 1

    if args.reset:
        stats["pins_cleared"] = shm.reset_readers(args.channel)
    if args.unlink:
        stats["unlinked"] = shm.unlink(args.channel)

    if args.json:
        print(json.dumps(stats))
    else:
        slots = stats.pop("slots")
        for k, v in stats.items():
            print(f"{k:16}: {v}")
        for i, s in enumerate(slots):
            print(f"slot {i}: readers={s['readers']} seq={s['seq']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
