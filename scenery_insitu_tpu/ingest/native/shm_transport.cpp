// Shared-memory frame transport: simulation -> renderer host bridge.
//
// TPU-native re-design of the reference's SysV double-buffer protocol
// (ShmAllocator.cpp / ShmBuffer.cpp / SemManager.cpp — producer writes a
// new timestep into the idle buffer and raises its semaphore; consumer
// attaches, raises its own; producer frees only when the consumer count
// drops; see SURVEY.md §2b "Protocol summary"). Differences, on purpose:
//
//  - POSIX shm_open/mmap + one process-shared semaphore in the control
//    block instead of SysV shmget/semget key juggling (the reference needed
//    ftok key toggling and stuck-semaphore recovery CLIs; names + atomics
//    make states inspectable and crash-robust).
//  - N-slot ring (default 3) generalizing the reference's 2-key toggle: one
//    slot being written, one latest, one held by a reader — the producer
//    NEVER blocks (the reference guaranteed that by falling back to heap
//    malloc, ShmAllocator.cpp:59-96; here acquire just returns the next
//    free slot, or -1 if a slow reader holds everything).
//  - seq numbers instead of semaphore counts: the consumer asks for "a
//    frame newer than the last I saw" (≅ ShmBuffer::update_key(wait),
//    ShmBuffer.cpp:84-112), blocking on the semaphore or polling.
//
// Single producer, multiple readers. The C ABI below is consumed from
// Python via ctypes (scenery_insitu_tpu/ingest/shm.py) and from the demo
// simulation producers in this directory.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <semaphore.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x53495456;  // "VTIS"
constexpr uint32_t kMaxSlots = 8;
constexpr size_t kHeaderBytes = 4096;    // control block, page aligned

struct SlotState {
  std::atomic<uint32_t> readers;
  std::atomic<uint64_t> seq;             // 0 = never published
  uint8_t pad[48];                       // avoid false sharing
};

struct Control {
  uint32_t magic;
  uint32_t nslots;
  uint64_t slot_size;
  std::atomic<uint64_t> next_seq;        // last published seq
  std::atomic<int32_t> latest;           // slot index of newest frame, -1
  std::atomic<uint32_t> waiters;
  std::atomic<uint32_t> writer_attached;
  sem_t fresh;                           // posted on publish when waited on
  std::atomic<uint64_t> frames_dropped;  // acquire failures (all slots busy)
  SlotState slots[kMaxSlots];
};

static_assert(sizeof(Control) <= kHeaderBytes, "control block too large");

struct Handle {
  Control* ctl;
  uint8_t* base;
  size_t map_bytes;
  int writing;                           // producer's in-flight slot, -1
  uint64_t last_seen;                    // consumer's newest consumed seq
};

size_t map_size(uint32_t nslots, uint64_t slot_size) {
  return kHeaderBytes + static_cast<size_t>(nslots) * slot_size;
}

Handle* map_channel(const char* name, int oflag, uint32_t nslots,
                    uint64_t slot_size) {
  int fd = shm_open(name, oflag, 0600);
  if (fd < 0) return nullptr;
  bool creating = (oflag & O_CREAT) != 0;
  size_t bytes;
  if (creating) {
    bytes = map_size(nslots, slot_size);
    if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < (off_t)kHeaderBytes) {
      close(fd);
      return nullptr;
    }
    bytes = static_cast<size_t>(st.st_size);
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Handle* h = new Handle();
  h->ctl = static_cast<Control*>(mem);
  h->base = static_cast<uint8_t*>(mem) + kHeaderBytes;
  h->map_bytes = bytes;
  h->writing = -1;
  h->last_seen = 0;
  return h;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- producer

// Create (or recreate) a channel. Returns an opaque handle or null.
void* shm_channel_create(const char* name, uint64_t slot_size,
                         uint32_t nslots) {
  if (nslots < 2 || nslots > kMaxSlots || slot_size == 0) return nullptr;
  shm_unlink(name);  // stale channels from crashed runs are superseded
  Handle* h = map_channel(name, O_CREAT | O_EXCL | O_RDWR, nslots, slot_size);
  if (!h) return nullptr;
  Control* c = h->ctl;
  std::memset(static_cast<void*>(c), 0, kHeaderBytes);
  c->nslots = nslots;
  c->slot_size = slot_size;
  c->latest.store(-1, std::memory_order_relaxed);
  sem_init(&c->fresh, /*pshared=*/1, 0);
  c->writer_attached.store(1, std::memory_order_relaxed);
  c->magic = kMagic;  // published last: consumers spin on it
  return h;
}

// Pointer to a writable slot for the next frame, or null if every other
// slot is held by a reader (producer never blocks; the frame is dropped —
// ≅ the reference's heap-malloc fallback keeping its producer lock-free).
void* shm_producer_acquire(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Control* c = h->ctl;
  int latest = c->latest.load(std::memory_order_acquire);
  for (uint32_t i = 0; i < c->nslots; ++i) {
    if (static_cast<int>(i) == latest) continue;  // a reader may grab it next
    if (c->slots[i].readers.load(std::memory_order_acquire) == 0) {
      h->writing = static_cast<int>(i);
      return h->base + static_cast<size_t>(i) * c->slot_size;
    }
  }
  c->frames_dropped.fetch_add(1, std::memory_order_relaxed);
  return nullptr;
}

// Publish the slot last acquired; returns its sequence number.
uint64_t shm_producer_publish(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Control* c = h->ctl;
  if (h->writing < 0) return 0;
  uint64_t seq = c->next_seq.fetch_add(1, std::memory_order_acq_rel) + 1;
  c->slots[h->writing].seq.store(seq, std::memory_order_release);
  c->latest.store(h->writing, std::memory_order_release);
  h->writing = -1;
  if (c->waiters.load(std::memory_order_acquire) > 0) sem_post(&c->fresh);
  return seq;
}

uint64_t shm_channel_frames_dropped(void* handle) {
  return static_cast<Handle*>(handle)
      ->ctl->frames_dropped.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------- consumer

// Open an existing channel; returns null until the producer created it.
void* shm_consumer_open(const char* name) {
  Handle* h = map_channel(name, O_RDWR, 0, 0);
  if (!h) return nullptr;
  if (h->ctl->magic != kMagic) {  // not yet initialized
    munmap(h->ctl, h->map_bytes);
    delete h;
    return nullptr;
  }
  return h;
}

uint64_t shm_channel_slot_size(void* handle) {
  return static_cast<Handle*>(handle)->ctl->slot_size;
}

uint32_t shm_channel_nslots(void* handle) {
  return static_cast<Handle*>(handle)->ctl->nslots;
}

// Acquire the newest frame strictly newer than the consumer's last one.
// timeout_ms: 0 = poll once, <0 = wait forever. On success pins the slot
// (readers++), stores the data pointer + seq, returns slot index; -1 on
// timeout. Release with shm_consumer_release.
int32_t shm_consumer_latest(void* handle, int64_t timeout_ms, void** data,
                            uint64_t* seq_out) {
  Handle* h = static_cast<Handle*>(handle);
  Control* c = h->ctl;
  for (;;) {
    int32_t l = c->latest.load(std::memory_order_acquire);
    if (l >= 0) {
      uint64_t seq = c->slots[l].seq.load(std::memory_order_acquire);
      if (seq > h->last_seen) {
        // pin, then re-verify the slot still carries this frame (the
        // producer skips the latest slot, so a pinned latest is stable,
        // but latest may have moved between the load and the pin)
        c->slots[l].readers.fetch_add(1, std::memory_order_acq_rel);
        if (c->slots[l].seq.load(std::memory_order_acquire) == seq) {
          h->last_seen = seq;
          *data = h->base + static_cast<size_t>(l) * c->slot_size;
          if (seq_out) *seq_out = seq;
          return l;
        }
        c->slots[l].readers.fetch_sub(1, std::memory_order_acq_rel);
        continue;  // raced a publish; retry immediately
      }
    }
    if (timeout_ms == 0) return -1;
    c->waiters.fetch_add(1, std::memory_order_acq_rel);
    int rc;
    if (timeout_ms < 0) {
      rc = sem_wait(&c->fresh);
    } else {
      struct timespec ts;
      clock_gettime(CLOCK_REALTIME, &ts);
      ts.tv_sec += timeout_ms / 1000;
      ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (ts.tv_nsec >= 1000000000L) {
        ts.tv_sec += 1;
        ts.tv_nsec -= 1000000000L;
      }
      rc = sem_timedwait(&c->fresh, &ts);
    }
    c->waiters.fetch_sub(1, std::memory_order_acq_rel);
    if (rc != 0 && (errno == ETIMEDOUT)) return -1;
    // EINTR or success: re-check the ring
  }
}

void shm_consumer_release(void* handle, int32_t slot) {
  Handle* h = static_cast<Handle*>(handle);
  if (slot >= 0 && slot < static_cast<int32_t>(h->ctl->nslots))
    h->ctl->slots[slot].readers.fetch_sub(1, std::memory_order_acq_rel);
}

// ------------------------------------------------------- inspect / recover
//
// ≅ the reference's stuck-state debug CLIs sem_get.cpp (print semaphore
// state for a rank) and sem_reset.cpp (zero it to recover a wedged
// protocol). The ring's state is plain atomics in the control block, so
// inspection is a read and recovery is clearing stale reader pins left by
// crashed consumers.

// Fills out[0..7+2*nslots): nslots, slot_size, next_seq, latest(+1, so 0
// means "none"), waiters, writer_attached, frames_dropped, then per slot
// (readers, seq). Returns the number of u64s written, or 0 if out_len is
// too small.
uint32_t shm_channel_stats(void* handle, uint64_t* out, uint32_t out_len) {
  Handle* h = static_cast<Handle*>(handle);
  Control* c = h->ctl;
  uint32_t need = 7 + 2 * c->nslots;
  if (out_len < need) return 0;
  out[0] = c->nslots;
  out[1] = c->slot_size;
  out[2] = c->next_seq.load(std::memory_order_acquire);
  out[3] = static_cast<uint64_t>(c->latest.load(std::memory_order_acquire) + 1);
  out[4] = c->waiters.load(std::memory_order_acquire);
  out[5] = c->writer_attached.load(std::memory_order_acquire);
  out[6] = c->frames_dropped.load(std::memory_order_relaxed);
  for (uint32_t i = 0; i < c->nslots; ++i) {
    out[7 + 2 * i] = c->slots[i].readers.load(std::memory_order_acquire);
    out[8 + 2 * i] = c->slots[i].seq.load(std::memory_order_acquire);
  }
  return need;
}

// Clears all reader pins (crashed consumers leak them, which eventually
// starves shm_producer_acquire). Returns the number of pins cleared.
uint32_t shm_channel_reset_readers(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Control* c = h->ctl;
  uint32_t cleared = 0;
  for (uint32_t i = 0; i < c->nslots; ++i)
    cleared += c->slots[i].readers.exchange(0, std::memory_order_acq_rel);
  return cleared;
}

// ------------------------------------------------------------------ common

void shm_channel_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h) return;
  munmap(h->ctl, h->map_bytes);
  delete h;
}

int shm_channel_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
