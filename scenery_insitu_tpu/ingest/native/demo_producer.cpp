// Standalone simulation producer for transport tests and demos
// (≅ the reference's shm_mpiproducer.cpp: a built-in SHO particle sim used
// as the fake workload driving the shm transport, :85-143 — here with a
// scalar-field mode too, since the TPU renderer's volume path ingests
// grids).
//
// Usage: demo_producer <channel> <mode:field|particles|slab> <size> <frames>
//                      [period_ms=5] [rank=0] [nranks=1]
//   field:     size = grid side; slot = size^3 f32 (travelling Gaussian)
//   particles: size = particle count; slot = size*6 f32 (pos+vel, SHO)
//   slab:      this rank's z-slab [size/nranks, size, size] of the SAME
//              global travelling Gaussian (bit-identical rows to field
//              mode at the same frame) — one process per compute rank,
//              the multi-rank feed of the distributed renderer (≅ the
//              reference's per-rank MPI partners each updating their
//              DistributedVolumeRenderer slab, :136-160)
//
// Exits after <frames> publishes; prints one line per 100 frames.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <thread>
#include <vector>

extern "C" {
void* shm_channel_create(const char* name, uint64_t slot_size,
                         uint32_t nslots);
void* shm_producer_acquire(void* handle);
uint64_t shm_producer_publish(void* handle);
uint64_t shm_channel_frames_dropped(void* handle);
void shm_channel_close(void* handle);
int shm_channel_unlink(const char* name);
}

// One frame of the travelling Gaussian, global rows [z0, z1) of a
// size^3 grid. field mode passes the whole range; slab mode its slab —
// identical arithmetic, so slab frames are bit-equal to field rows.
static void fill_field(float* out, long size, long z0, long z1, long f) {
  const float cx = 0.5f + 0.3f * std::sin(0.05f * f);
  const float cy = 0.5f + 0.3f * std::cos(0.05f * f);
  const float cz = 0.5f;
  for (long z = z0; z < z1; ++z)
    for (long y = 0; y < size; ++y)
      for (long x = 0; x < size; ++x) {
        const float dx = (x + 0.5f) / size - cx;
        const float dy = (y + 0.5f) / size - cy;
        const float dz = (z + 0.5f) / size - cz;
        out[((z - z0) * size + y) * size + x] =
            std::exp(-(dx * dx + dy * dy + dz * dz) / 0.02f);
      }
}

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <channel> <field|particles|slab> <size> <frames> "
                 "[period_ms] [rank] [nranks]\n",
                 argv[0]);
    return 2;
  }
  const char* channel = argv[1];
  const bool field_mode = std::strcmp(argv[2], "field") == 0;
  const bool slab_mode = std::strcmp(argv[2], "slab") == 0;
  const long size = std::atol(argv[3]);
  const long frames = std::atol(argv[4]);
  const long period_ms = argc > 5 ? std::atol(argv[5]) : 5;
  const long rank = argc > 6 ? std::atol(argv[6]) : 0;
  const long nranks = argc > 7 ? std::atol(argv[7]) : 1;
  if (slab_mode && (nranks < 1 || size % nranks || rank < 0
                    || rank >= nranks)) {
    std::fprintf(stderr, "slab mode needs 0 <= rank < nranks and "
                 "size %% nranks == 0 (got size=%ld rank=%ld nranks=%ld)\n",
                 size, rank, nranks);
    return 2;
  }
  if (!slab_mode && (rank != 0 || nranks != 1)) {
    std::fprintf(stderr, "rank/nranks are slab-mode args (mode %s would "
                 "silently publish the wrong z-window)\n", argv[2]);
    return 2;
  }
  const long dn = slab_mode ? size / nranks : size;

  const uint64_t slot =
      (field_mode || slab_mode) ? sizeof(float) * dn * size * size
                                : sizeof(float) * size * 6;
  void* h = shm_channel_create(channel, slot, 3);
  if (!h) {
    std::perror("shm_channel_create");
    return 1;
  }

  // SHO particle state (positions in [0,1), omega^2 = 4 about the center —
  // same toy dynamics the reference's producer used)
  const bool grid_mode = field_mode || slab_mode;
  std::vector<float> pos(grid_mode ? 0 : size * 3),
      vel(grid_mode ? 0 : size * 3);
  for (long i = 0; i < (long)pos.size(); ++i) {
    pos[i] = static_cast<float>((i * 2654435761u % 1000) / 1000.0);
    vel[i] = 0.0f;
  }

  const float dt = 0.005f, omega2 = 4.0f;
  for (long f = 0; f < frames; ++f) {
    float* out = static_cast<float*>(shm_producer_acquire(h));
    if (out) {
      if (grid_mode) {
        // travelling Gaussian blob: analytic, cheap, visibly animated
        fill_field(out, size, rank * dn, (rank + 1) * dn, f);
      } else {
        for (long i = 0; i < size * 3; ++i) {
          const float acc = -omega2 * (pos[i] - 0.5f);
          vel[i] += dt * acc;
          pos[i] += dt * vel[i];
        }
        std::memcpy(out, pos.data(), pos.size() * sizeof(float));
        std::memcpy(out + size * 3, vel.data(), vel.size() * sizeof(float));
      }
      shm_producer_publish(h);
    }
    if (f % 100 == 0)
      std::printf("produced %ld/%ld (dropped %llu)\n", f, frames,
                  (unsigned long long)shm_channel_frames_dropped(h));
    if (period_ms > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(period_ms));
  }
  std::printf("done: %ld frames, dropped %llu\n", frames,
              (unsigned long long)shm_channel_frames_dropped(h));
  shm_channel_close(h);
  return 0;
}
