// LZ4 block-format codec — clean-room implementation of the PUBLIC
// block format (token / literal-run / 2-byte LE offset / match-run with
// 255-continuation lengths, 64 KB window, minmatch 4), written for the
// fast-codec role the reference gives LZ4 on its VDI wire path
// (VDICompositingTest.kt:251-304 compresses each per-rank segment before
// the all-to-all; VDICompressionBenchmarks.kt:23-372 benchmarks the LZ4
// family). Greedy single-pass compressor with a 64 Ki-entry hash table;
// the decompressor bounds-checks every read/write so corrupt input
// returns 0 instead of scribbling.
//
// Format notes (spec end conditions honored):
//   - last 5 bytes of the input are always literals;
//   - no match starts within the last 12 bytes;
//   - offsets are 1..65535 (matches beyond the window are not emitted).
// Streams produced here decode with any conformant LZ4 block decoder.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kTableBits = 16;
constexpr uint32_t kTableSize = 1u << kTableBits;
constexpr size_t kMinMatch = 4;
constexpr size_t kMaxOffset = 65535;
constexpr size_t kEndLiterals = 5;   // last 5 bytes stay literal
constexpr size_t kMatchGuard = 12;   // no match starts in last 12 bytes

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kTableBits);
}

// write a 15+ length with 255-continuations; returns new op or null on
// overflow
inline uint8_t* put_len(uint8_t* op, const uint8_t* oend, size_t rest) {
  while (rest >= 255) {
    if (op >= oend) return nullptr;
    *op++ = 255;
    rest -= 255;
  }
  if (op >= oend) return nullptr;
  *op++ = static_cast<uint8_t>(rest);
  return op;
}

}  // namespace

extern "C" {

// worst case: every byte literal (+run headers) + one final token
uint64_t lz4b_bound(uint64_t n) { return n + n / 255 + 16; }

// returns compressed size, or 0 when dst_cap is too small (or n == 0 —
// the caller handles empty payloads)
uint64_t lz4b_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                       uint64_t dst_cap) {
  if (n == 0 || !src || !dst) return 0;
  if (n > 0xfffffffeull) return 0;  // positions are stored as u32 + 1
  const uint8_t* ip = src;
  const uint8_t* iend = src + n;
  const uint8_t* anchor = src;
  const uint8_t* mflimit = n > kMatchGuard ? iend - kMatchGuard : src;
  const uint8_t* matchlimit = n > kEndLiterals ? iend - kEndLiterals : src;
  uint8_t* op = dst;
  uint8_t* oend = dst + dst_cap;

  std::vector<uint32_t> table(kTableSize, 0);  // position + 1; 0 = empty

  while (ip < mflimit) {
    const uint32_t h = hash4(read32(ip));
    const uint32_t cand = table[h];
    table[h] = static_cast<uint32_t>(ip - src) + 1;
    const uint8_t* match = src + cand - 1;
    if (!cand || static_cast<size_t>(ip - match) > kMaxOffset ||
        read32(match) != read32(ip)) {
      ++ip;
      continue;
    }
    // extend the match forward (stays clear of the end-literal zone);
    // 8-byte xor+ctz steps, byte tail
    const uint8_t* i2 = ip + kMinMatch;
    const uint8_t* m2 = match + kMinMatch;
    bool mismatch = false;
    while (i2 + 8 <= matchlimit) {
      const uint64_t x = read64(i2) ^ read64(m2);
      if (x) {
        i2 += __builtin_ctzll(x) >> 3;
        mismatch = true;
        break;
      }
      i2 += 8;
      m2 += 8;
    }
    if (!mismatch)
      while (i2 < matchlimit && *i2 == *m2) {
        ++i2;
        ++m2;
      }
    const size_t mlen = static_cast<size_t>(i2 - ip) - kMinMatch;  // extra
    const size_t lit = static_cast<size_t>(ip - anchor);

    if (op >= oend) return 0;
    uint8_t* token = op++;
    *token = static_cast<uint8_t>((lit >= 15 ? 15 : lit) << 4);
    if (lit >= 15 && !(op = put_len(op, oend, lit - 15))) return 0;
    if (op + lit + 2 > oend) return 0;
    std::memcpy(op, anchor, lit);
    op += lit;
    const size_t off = static_cast<size_t>(ip - match);
    *op++ = static_cast<uint8_t>(off & 0xff);
    *op++ = static_cast<uint8_t>(off >> 8);
    *token |= static_cast<uint8_t>(mlen >= 15 ? 15 : mlen);
    if (mlen >= 15 && !(op = put_len(op, oend, mlen - 15))) return 0;

    ip = i2;
    anchor = ip;
    if (ip < mflimit)  // seed the table inside the skipped match
      table[hash4(read32(ip - 2))] =
          static_cast<uint32_t>(ip - 2 - src) + 1;
  }

  // final run: everything left is literal
  const size_t lit = static_cast<size_t>(iend - anchor);
  if (op >= oend) return 0;
  uint8_t* token = op++;
  *token = static_cast<uint8_t>((lit >= 15 ? 15 : lit) << 4);
  if (lit >= 15 && !(op = put_len(op, oend, lit - 15))) return 0;
  if (op + lit > oend) return 0;
  std::memcpy(op, anchor, lit);
  op += lit;
  return static_cast<uint64_t>(op - dst);
}

// returns decompressed size, or 0 on corrupt input / undersized dst
uint64_t lz4b_decompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                         uint64_t dst_cap) {
  if (!src || !dst) return 0;
  const uint8_t* ip = src;
  const uint8_t* iend = src + n;
  uint8_t* op = dst;
  uint8_t* oend = dst + dst_cap;

  while (ip < iend) {
    const uint8_t token = *ip++;
    size_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        lit += b;
      } while (b == 255);
    }
    if (static_cast<size_t>(iend - ip) < lit ||
        static_cast<size_t>(oend - op) < lit)
      return 0;
    std::memcpy(op, ip, lit);
    ip += lit;
    op += lit;
    if (ip >= iend) break;  // last sequence carries no match

    if (iend - ip < 2) return 0;
    const size_t off = static_cast<size_t>(ip[0]) |
                       (static_cast<size_t>(ip[1]) << 8);
    ip += 2;
    if (off == 0 || static_cast<size_t>(op - dst) < off) return 0;
    size_t mlen = token & 15;
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    mlen += kMinMatch;
    if (static_cast<size_t>(oend - op) < mlen) return 0;
    const uint8_t* m = op - off;
    if (off >= mlen) {
      std::memcpy(op, m, mlen);          // disjoint: straight copy
    } else {
      for (size_t i = 0; i < mlen; ++i)  // overlap is the point (RLE)
        op[i] = m[i];
    }
    op += mlen;
  }
  return static_cast<uint64_t>(op - dst);
}

}  // extern "C"
