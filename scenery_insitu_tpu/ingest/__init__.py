from scenery_insitu_tpu.ingest.shm import (  # noqa: F401
    ShmConsumer, ShmProducer, ShmShardedVolumeSource, ShmVolumeSource,
    ensure_built)
