"""Python bindings for the C++ shared-memory frame transport
(SURVEY.md §7 step 7 — layer L1, the sim↔renderer operator boundary).

The reference crossed this boundary with SysV shm + JNI
``NewDirectByteBuffer`` zero-copy handoff (SharedSpheresExample.cpp:54);
here ctypes maps the C ABI of ``native/shm_transport.cpp`` and the consumer
exposes each pinned slot as a zero-copy numpy view, which ``device_put``
then ships host→HBM (the one copy a TPU cannot avoid — SURVEY.md §7 "hard
parts"; overlap it with compute by dispatching before blocking).

``ShmVolumeSource`` adapts a channel to the session loop's sim-facade
protocol (``advance(n)`` + ``.field``), so an external C++/OpenFPM-style
simulation can drive InSituSession exactly like the built-in sims — the
``addVolume/updateVolume`` operator boundary of the reference
(DistributedVolumes.kt:147-250) collapses to "publish a frame".
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
# SITPU_NATIVE_BUILD selects the Makefile build variant: "build" (the
# default) or "build-asan" (`make asan` — the
# -fsanitize=address,undefined instrumented .so the CI sanitizer job
# runs the ingest tests against; needs LD_PRELOAD of the ASan runtime,
# see native/Makefile)
_BUILD_DIR = os.environ.get("SITPU_NATIVE_BUILD", "build")
_MAKE_TARGET = "asan" if _BUILD_DIR == "build-asan" else "all"
_LIB_PATH = os.path.join(_NATIVE_DIR, _BUILD_DIR, "libshm_transport.so")
DEMO_PRODUCER = os.path.join(_NATIVE_DIR, _BUILD_DIR, "demo_producer")

_lib = None


def _sources_mtime() -> float:
    newest = 0.0
    for name in os.listdir(_NATIVE_DIR):
        if name.endswith((".cpp", ".h", ".hpp")) or name == "Makefile":
            newest = max(newest, os.path.getmtime(
                os.path.join(_NATIVE_DIR, name)))
    return newest


def ensure_built(force: bool = False) -> str:
    """Build the native library on first use, and REBUILD when any source
    is newer than the binary — a stale .so from an older checkout otherwise
    fails at ctypes symbol lookup with an opaque 'undefined symbol'."""
    stale = (not os.path.exists(_LIB_PATH)
             or os.path.getmtime(_LIB_PATH) < _sources_mtime())
    if force or stale:
        subprocess.run(["make", "-C", _NATIVE_DIR, _MAKE_TARGET],
                       check=True, capture_output=True)
    return _LIB_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(ensure_built())
    lib.shm_channel_create.restype = ctypes.c_void_p
    lib.shm_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_uint32]
    lib.shm_producer_acquire.restype = ctypes.c_void_p
    lib.shm_producer_acquire.argtypes = [ctypes.c_void_p]
    lib.shm_producer_publish.restype = ctypes.c_uint64
    lib.shm_producer_publish.argtypes = [ctypes.c_void_p]
    lib.shm_channel_frames_dropped.restype = ctypes.c_uint64
    lib.shm_channel_frames_dropped.argtypes = [ctypes.c_void_p]
    lib.shm_consumer_open.restype = ctypes.c_void_p
    lib.shm_consumer_open.argtypes = [ctypes.c_char_p]
    lib.shm_channel_slot_size.restype = ctypes.c_uint64
    lib.shm_channel_slot_size.argtypes = [ctypes.c_void_p]
    lib.shm_channel_nslots.restype = ctypes.c_uint32
    lib.shm_channel_nslots.argtypes = [ctypes.c_void_p]
    lib.shm_consumer_latest.restype = ctypes.c_int32
    lib.shm_consumer_latest.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_void_p),
                                        ctypes.POINTER(ctypes.c_uint64)]
    lib.shm_consumer_release.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.shm_channel_close.argtypes = [ctypes.c_void_p]
    lib.shm_channel_unlink.restype = ctypes.c_int
    lib.shm_channel_unlink.argtypes = [ctypes.c_char_p]
    lib.shm_channel_stats.restype = ctypes.c_uint32
    lib.shm_channel_stats.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_uint32]
    lib.shm_channel_reset_readers.restype = ctypes.c_uint32
    lib.shm_channel_reset_readers.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def channel_stats(channel: str) -> dict:
    """Inspect a live channel's control block (≅ sem_get.cpp's semaphore
    dump, reference src/test/cpp/sem_get.cpp). Raises FileNotFoundError if
    the channel does not exist."""
    lib = _load()
    h = lib.shm_consumer_open(channel.encode())
    if not h:
        raise FileNotFoundError(f"no shm channel {channel!r}")
    try:
        # size the buffer from the channel's actual slot count instead of a
        # fixed 32 (which silently relied on kMaxSlots=8 in the C++ side)
        nslots_c = int(lib.shm_channel_nslots(h))
        need = 7 + 2 * nslots_c
        buf = (ctypes.c_uint64 * need)()
        n = lib.shm_channel_stats(h, buf, need)
        if n == 0:
            raise OSError(
                f"shm_channel_stats returned no data for {channel!r} "
                f"(buffer {need} u64, nslots {nslots_c})")
        vals = list(buf[:n])
        nslots = int(vals[0])
        return {
            "channel": channel,
            "nslots": nslots,
            "slot_bytes": int(vals[1]),
            "last_seq": int(vals[2]),
            "latest_slot": int(vals[3]) - 1,
            "waiters": int(vals[4]),
            "writer_attached": bool(vals[5]),
            "frames_dropped": int(vals[6]),
            "slots": [{"readers": int(vals[7 + 2 * i]),
                       "seq": int(vals[8 + 2 * i])}
                      for i in range(nslots)],
        }
    finally:
        lib.shm_channel_close(h)


def reset_readers(channel: str) -> int:
    """Clear stale reader pins left by crashed consumers (≅ sem_reset.cpp's
    stuck-semaphore recovery). Returns the number of pins cleared."""
    lib = _load()
    h = lib.shm_consumer_open(channel.encode())
    if not h:
        raise FileNotFoundError(f"no shm channel {channel!r}")
    try:
        return int(lib.shm_channel_reset_readers(h))
    finally:
        lib.shm_channel_close(h)


def unlink(channel: str) -> bool:
    """Remove a channel from the namespace (live handles keep their maps)."""
    return _load().shm_channel_unlink(channel.encode()) == 0


class ShmProducer:
    """Publish fixed-shape f32 frames (the simulation side; ≅ ShmAllocator's
    shm_alloc/shm_free cycle, ShmAllocator.cpp:59-151)."""

    def __init__(self, channel: str, shape: Sequence[int], nslots: int = 3):
        self.lib = _load()
        self.shape = tuple(shape)
        self.nbytes = int(np.prod(self.shape)) * 4
        self.channel = channel
        self.handle = self.lib.shm_channel_create(
            channel.encode(), self.nbytes, nslots)
        if not self.handle:
            raise OSError(f"could not create shm channel {channel!r}")

    def publish(self, frame: np.ndarray) -> int:
        """Copy one frame in and publish; returns seq (0 = dropped: every
        writable slot was pinned by slow readers — the producer never
        blocks, matching the reference's guarantee)."""
        frame = np.ascontiguousarray(frame, np.float32)
        if frame.shape != self.shape:
            raise ValueError(f"frame shape {frame.shape} != {self.shape}")
        ptr = self.lib.shm_producer_acquire(self.handle)
        if not ptr:
            return 0
        ctypes.memmove(ptr, frame.ctypes.data, self.nbytes)
        return self.lib.shm_producer_publish(self.handle)

    @property
    def frames_dropped(self) -> int:
        return self.lib.shm_channel_frames_dropped(self.handle)

    def close(self, unlink: bool = True) -> None:
        if self.handle:
            self.lib.shm_channel_close(self.handle)
            self.handle = None
            if unlink:
                self.lib.shm_channel_unlink(self.channel.encode())


class ShmConsumer:
    """Receive frames (the renderer side; ≅ ShmBuffer's
    update_key/attach/detach cycle, ShmBuffer.cpp:29-112)."""

    def __init__(self, channel: str, shape: Sequence[int],
                 timeout_ms: int = 5000, poll_interval_ms: int = 20):
        import time
        self.lib = _load()
        self.shape = tuple(shape)
        deadline = time.monotonic() + timeout_ms / 1000.0
        self.handle = None
        while time.monotonic() < deadline:         # producer may start later
            h = self.lib.shm_consumer_open(channel.encode())
            if h:
                self.handle = h
                break
            time.sleep(poll_interval_ms / 1000.0)
        if not self.handle:
            raise TimeoutError(f"shm channel {channel!r} never appeared")
        slot = self.lib.shm_channel_slot_size(self.handle)
        want = int(np.prod(self.shape)) * 4
        if slot != want:
            self.lib.shm_channel_close(self.handle)
            raise ValueError(f"channel slot size {slot} != expected {want}")

    def latest(self, timeout_ms: int = -1, copy: bool = True
               ) -> Optional[Tuple[np.ndarray, int]]:
        """Newest frame strictly newer than the last seen, or None on
        timeout. copy=False returns the zero-copy view WITHOUT releasing
        the slot — call release(slot) (attr ``.slot`` on the array) when
        done, exactly the reference's detach discipline."""
        data = ctypes.c_void_p()
        seq = ctypes.c_uint64()
        idx = self.lib.shm_consumer_latest(self.handle, timeout_ms,
                                           ctypes.byref(data),
                                           ctypes.byref(seq))
        if idx < 0:
            return None
        n = int(np.prod(self.shape))
        buf = (ctypes.c_float * n).from_address(data.value)
        view = np.frombuffer(buf, np.float32).reshape(self.shape)
        if copy:
            out = view.copy()
            self.lib.shm_consumer_release(self.handle, idx)
            return out, seq.value

        class _Pinned(np.ndarray):      # ndarray subclass carrying the slot
            pass

        pinned = view.view(_Pinned)
        pinned.flags.writeable = False
        pinned.slot = idx
        return pinned, seq.value

    def release(self, slot: int) -> None:
        self.lib.shm_consumer_release(self.handle, slot)

    def close(self) -> None:
        if self.handle:
            self.lib.shm_channel_close(self.handle)
            self.handle = None


class ShmShardedVolumeSource:
    """Multi-rank external feed for the DISTRIBUTED pipeline: one shm
    channel per compute rank (z-slab order), assembled into one
    mesh-sharded global ``jax.Array`` — each slab is ``device_put`` onto
    its own mesh device and stitched with
    ``make_array_from_single_device_arrays``, so no global host-side
    copy ever exists and the session's ``shard_volume`` re-placement is
    a no-op (the array is already committed with the pipeline's
    sharding). This is the operator boundary the reference crossed with
    per-rank MPI partners each updating their renderer's slab
    (DistributedVolumeRenderer.kt:136-160); here N external producer
    processes feed an InSituSession over a ``Mesh`` exactly like the
    built-in sharded sims.

    ``coherent=True`` (default) additionally requires the per-rank
    sequence numbers of one assembled frame to MATCH — the renderer
    never mixes simulation timesteps across slabs (the reference renders
    whatever each rank last delivered; pass ``coherent=False`` for that
    semantics). Coherence matching assumes lockstep producers (each
    publish succeeds: the ring overwrites, it never drops without
    pinned readers). Before the FIRST frame set is assembled a timeout
    raises, naming the per-rank seqs so a desync is diagnosable; after
    that ``advance`` paces to the producers — it blocks up to
    ``frame_timeout_ms`` for a strictly newer set, then keeps rendering
    the last one (the single-channel source's semantics).

    ``timeout_ms`` bounds channel appearance + the first frame set;
    ``frame_timeout_ms`` (default: ``timeout_ms``) bounds each
    subsequent wait for a newer set.
    """

    def __init__(self, channels: Sequence[str], slab_shape: Sequence[int],
                 mesh, axis_name: Optional[str] = None,
                 timeout_ms: int = 10000, coherent: bool = True,
                 poll_interval_ms: int = 5,
                 frame_timeout_ms: Optional[int] = None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.kind = "external"
        axis = axis_name or mesh.axis_names[0]
        n = mesh.shape[axis]
        if len(channels) != n:
            raise ValueError(f"{len(channels)} channels for a mesh of "
                             f"{n} devices along {axis!r} — need one "
                             "channel per rank, z order")
        self.channels = list(channels)
        self.slab_shape = tuple(slab_shape)
        dn = self.slab_shape[0]
        self.global_shape = (dn * n,) + self.slab_shape[1:]
        self.timeout_ms = timeout_ms
        self.frame_timeout_ms = (timeout_ms if frame_timeout_ms is None
                                 else frame_timeout_ms)
        self.coherent = coherent
        self.poll_interval_ms = poll_interval_ms
        self._jax = jax
        self.sharding = NamedSharding(mesh, P(axis, None, None))
        # mesh device of each rank's shard, in z order (shard r owns
        # global rows [r*dn, (r+1)*dn))
        dmap = self.sharding.addressable_devices_indices_map(
            self.global_shape)
        by_rank = {}
        for dev, idx in dmap.items():
            by_rank[(idx[0].start or 0) // dn] = dev
        self._devices = [by_rank[r] for r in range(n)]
        self.consumers = [ShmConsumer(c, self.slab_shape,
                                      timeout_ms=timeout_ms)
                          for c in channels]
        self._held = [None] * n        # newest (frame, seq) seen per rank
        self._field = None
        self.last_seqs: Tuple[int, ...] = ()
        self.stalled = False

    def _refresh(self, wait_ms: int) -> None:
        for r, con in enumerate(self.consumers):
            got = con.latest(timeout_ms=wait_ms)
            if got is not None:
                self._held[r] = got

    def _aligned(self) -> bool:
        if any(h is None for h in self._held):
            return False
        if not self.coherent:
            return True
        seqs = {h[1] for h in self._held}
        return len(seqs) == 1

    def advance(self, n: int = 1) -> None:   # n meaningless for external
        import time

        from scenery_insitu_tpu import obs as _obs

        # while stalled, one non-blocking refresh pass per advance (same
        # policy as ShmVolumeSource: a dead producer set must not
        # throttle the render loop to one frame per timeout)
        wait_ms = (self.timeout_ms if self._field is None
                   else 0 if self.stalled else self.frame_timeout_ms)
        deadline = time.monotonic() + wait_ms / 1000.0
        first = True
        while True:
            # first pass is free (producers may have already published);
            # later passes wait a poll interval inside the consumer
            self._refresh(0 if first else self.poll_interval_ms)
            first = False
            # only a STRICTLY NEWER aligned set completes the wait —
            # otherwise a fast render loop would busy-spin re-rendering
            # the same frame instead of pacing to the producers
            if self._aligned():
                seqs = tuple(h[1] for h in self._held)
                if seqs != self.last_seqs:
                    arrs = [self._jax.device_put(h[0], d)
                            for h, d in zip(self._held, self._devices)]
                    self._field = \
                        self._jax.make_array_from_single_device_arrays(
                            self.global_shape, self.sharding, arrs)
                    self.last_seqs = seqs
                    if self.stalled:
                        self.stalled = False
                        _obs.get_recorder().count(
                            "ingest_stall_recoveries")
                        _obs.get_recorder().event(
                            "ingest_recovered",
                            seqs=[int(s) for s in seqs])
                    return
            if time.monotonic() > deadline:
                if self._field is not None:
                    if not self.stalled:
                        self.stalled = True
                        _obs.get_recorder().count("ingest_stalls")
                        _obs.degrade(
                            "ingest.stall", "live producer frames",
                            "re-rendering last-good frame",
                            "no strictly-newer coherent shm frame set "
                            f"within frame_timeout_ms="
                            f"{self.frame_timeout_ms}; a producer "
                            "stalled or died", warn=False)
                    return                     # keep rendering last frame
                held = [None if h is None else h[1] for h in self._held]
                raise TimeoutError(
                    f"no {'coherent ' if self.coherent else ''}frame set "
                    f"from {self.channels} within {wait_ms} ms "
                    f"(per-rank seqs: {held})")

    @property
    def field(self):
        if self._field is None:
            self.advance(1)
        return self._field

    def stats(self) -> list:
        """Per-rank channel control blocks (seq/drop/reader state)."""
        return [channel_stats(c) for c in self.channels]

    def close(self) -> None:
        for con in self.consumers:
            con.close()


class ShmVolumeSource:
    """Session sim-adapter over a shm channel: ``advance(n)`` pulls the
    newest frame (blocking until one arrives), ``.field`` is the device
    array. Plugs an EXTERNAL simulation into InSituSession.

    Stall supervision (docs/ROBUSTNESS.md): when no strictly-newer frame
    arrives within ``frame_timeout_ms`` (default: ``timeout_ms``) the
    source marks itself STALLED — minted once per episode on the
    ``ingest.stall`` ledger — and keeps rendering the last-good frame;
    while stalled, ``advance`` polls without blocking so a dead producer
    cannot throttle the render loop to one frame per timeout. The
    moment frames resume the stall clears (``ingest_stall_recoveries``
    counter + ``ingest_recovered`` event)."""

    def __init__(self, channel: str, grid: Sequence[int],
                 timeout_ms: int = 10000, device_put: bool = True,
                 frame_timeout_ms: Optional[int] = None):
        import jax

        self.kind = "external"
        self.consumer = ShmConsumer(channel, grid, timeout_ms=timeout_ms)
        self.timeout_ms = timeout_ms
        self.frame_timeout_ms = (timeout_ms if frame_timeout_ms is None
                                 else frame_timeout_ms)
        self._device_put = device_put
        self._jax = jax
        self._field = None
        self.stalled = False
        self.stall_count = 0
        self.last_seq = None

    def advance(self, n: int) -> None:   # n is meaningless for external sims
        from scenery_insitu_tpu import obs as _obs

        # while stalled, poll non-blocking: the loop keeps pacing on
        # last-good data instead of stalling frame_timeout_ms per frame
        wait = (self.timeout_ms if self._field is None
                else 0 if self.stalled else self.frame_timeout_ms)
        got = self.consumer.latest(timeout_ms=wait)
        if got is None:
            if self._field is None:
                raise TimeoutError("no frame from external simulation")
            if not self.stalled:
                self.stalled = True
                self.stall_count += 1
                _obs.get_recorder().count("ingest_stalls")
                _obs.degrade(
                    "ingest.stall", "live producer frames",
                    "re-rendering last-good frame",
                    f"no strictly-newer shm frame within "
                    f"frame_timeout_ms={self.frame_timeout_ms}; "
                    "producer stalled or dead", warn=False)
            return                        # keep rendering the last frame
        frame, seq = got
        if self.stalled:
            self.stalled = False
            _obs.get_recorder().count("ingest_stall_recoveries")
            _obs.get_recorder().event("ingest_recovered", seq=int(seq))
        self.last_seq = seq
        self._field = (self._jax.device_put(frame) if self._device_put
                       else frame)

    @property
    def field(self):
        if self._field is None:
            self.advance(1)
        return self._field
