"""The VDI edge-serving tier (ROADMAP item 2; docs/SERVING.md).

``python -m scenery_insitu_tpu.serve`` runs the edge process:
`ViewerServer` subscribes to a composited VDI stream and answers N
concurrent client cameras per frame from one batched device dispatch
(`ops.vdi_novel.render_vdi_batch`) — sim + march + composite stay O(1)
while viewer cost scales on this separate, cacheable tier. `ViewerClient`
is the viewer endpoint (typed answers, heartbeats, viewer-side
reprojection between keyframes).
"""

from scenery_insitu_tpu.serve.client import (ServeDrop, ViewerClient,
                                             ViewerFrame)
from scenery_insitu_tpu.serve.reproject import reproject_planar
from scenery_insitu_tpu.serve.server import (TIERS, ViewerServer,
                                             camera_from_message)

__all__ = ["ViewerServer", "ViewerClient", "ViewerFrame", "ServeDrop",
           "reproject_planar", "camera_from_message", "TIERS"]
