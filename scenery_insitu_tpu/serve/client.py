"""Viewer client of the edge-serving tier (docs/SERVING.md "Client
protocol").

`ViewerClient` speaks the serve protocol over one DEALER socket: hello
(tier negotiation through admission control), camera requests, typed
answers (frame / shed), heartbeats so the server can tell a quiet viewer
from a dead one, and a clean bye. Every answer is validated (msgpack
header, CRC, declared shape × itemsize) BEFORE decode — a corrupt or
truncated answer is a typed `ServeDrop`, never an exception, mirroring
the `VDISubscriber` hardening contract (docs/ROBUSTNESS.md).

Between server keyframes, `render_local` warps the last answered frame
onto a new camera viewer-side (`serve/reproject.py`) — the small-motion
latency path that needs no round trip at all.
"""

from __future__ import annotations

import os
import time
import zlib
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from scenery_insitu_tpu.config import FaultConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.runtime.streaming import (_msgpack, _zmq,
                                                  make_camera_message)


@dataclass(frozen=True)
class ServeDrop:
    """Typed record of one answer the client refused or one refusal the
    server sent: ``kind`` is ``"shed"`` (admission control),
    ``"integrity"`` (CRC/size/shape mismatch) or ``"malformed"``
    (header unparseable)."""

    kind: str
    reason: str
    seq: Optional[int] = None


@dataclass
class ViewerFrame:
    """One answered view: ``image`` is f32[4, H, W] premultiplied
    (wire-tier u8 payloads are dequantized here), ``wire_bytes`` is what
    actually crossed the socket for the pixel blob."""

    image: np.ndarray
    frame: int
    seq: int
    tier: str
    stale: bool
    cached: bool
    wire_bytes: int


class ViewerClient:
    """One viewer endpoint. Single-threaded: `request` then `poll` (or
    `render` for the request→answer round trip)."""

    def __init__(self, connect: str, tier: str = "proxy",
                 identity: Optional[bytes] = None,
                 fault: Optional[FaultConfig] = None):
        zmq = _zmq()
        self.tier = tier
        self.fault = fault or FaultConfig()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.DEALER)
        self.identity = identity or os.urandom(8).hex().encode()
        self.sock.setsockopt(zmq.IDENTITY, self.identity)
        self.sock.connect(connect)
        self._seq = 0
        self._cams = {}                    # seq -> Camera (reprojection)
        self.last: Optional[ViewerFrame] = None
        self.last_camera: Optional[Camera] = None
        self.stats = {"answers": 0, "sheds": 0, "drops": 0, "bytes": 0,
                      "cache_hits": 0, "stale_answers": 0}
        self._last_send = time.monotonic()

    # ------------------------------------------------------------- sends
    def hello(self, timeout_ms: int = 5000
              ) -> Union[dict, ServeDrop, None]:
        """Introduce this viewer (tier negotiation). Returns the welcome
        dict, a ``shed`` ServeDrop (admission control refused), or None
        on timeout."""
        self.sock.send(_msgpack().packb({"type": "hello",
                                         "tier": self.tier}))
        self._last_send = time.monotonic()
        deadline = time.monotonic() + timeout_ms / 1000.0
        while time.monotonic() < deadline:
            left = max(0, int((deadline - time.monotonic()) * 1000))
            got = self.poll(timeout_ms=left)
            if isinstance(got, ServeDrop) and got.seq is not None:
                continue   # belongs to an earlier camera request —
                #            hellos carry no seq, so only a seq-less
                #            drop can be THIS hello's refusal
            if isinstance(got, dict) or isinstance(got, ServeDrop) \
                    or got is None:
                return got
            # a late frame answer from an earlier request — keep waiting

    def request(self, cam: Camera, seq: Optional[int] = None) -> int:
        """Send one camera request; returns its sequence number."""
        if seq is None:
            self._seq += 1
            seq = self._seq
        msg = make_camera_message(cam)
        msg["seq"] = int(seq)
        # carry the tier on every request: a viewer that never said
        # hello is implicitly admitted, and without this its answers
        # would silently arrive at serve.default_tier
        msg["tier"] = self.tier
        self.sock.send(_msgpack().packb(msg))
        self._last_send = time.monotonic()
        self._cams[int(seq)] = cam
        # bound the in-flight map — an answer can only reference a
        # recent seq, and a shed request's camera must not leak
        while len(self._cams) > 32:
            self._cams.pop(next(iter(self._cams)))
        return seq

    def heartbeat(self) -> None:
        self.sock.send(_msgpack().packb({"hb": 1}))
        self._last_send = time.monotonic()

    def maybe_heartbeat(self) -> bool:
        """Heartbeat only after ``fault.heartbeat_period_s`` of send
        silence (the PR-11 pacer convention) — call from the viewer's
        idle loop to stay admitted past ``serve.client_timeout_s``
        without spamming the server."""
        if time.monotonic() - self._last_send \
                < self.fault.heartbeat_period_s:
            return False
        self.heartbeat()
        return True

    def bye(self) -> None:
        self.sock.send(_msgpack().packb({"type": "bye"}))

    # ----------------------------------------------------------- receive
    def poll(self, timeout_ms: int = 1000
             ) -> Union[None, dict, ServeDrop, ViewerFrame]:
        """One answer: a `ViewerFrame`, a welcome dict, a typed
        `ServeDrop` (shed / refused answer), or None on timeout."""
        if not self.sock.poll(timeout_ms):
            return None
        parts = self.sock.recv_multipart()
        msgpack = _msgpack()
        try:
            h = msgpack.unpackb(parts[0])
            if not isinstance(h, dict):
                raise TypeError("header is not a map")
        except Exception:  # sitpu-lint: disable=SITPU-LEDGER (client-side typed drop, counted in stats)
            self.stats["drops"] += 1
            return ServeDrop("malformed", "unparseable answer header")
        kind = h.get("type")
        if kind == "welcome":
            # adopt the NEGOTIATED tier (an unknown request degrades to
            # the server's default) so later requests carry it — here,
            # not in hello(): a fire-and-forget hello(timeout_ms=0)
            # consumes its welcome through a later poll()
            if "tier" in h:
                self.tier = h["tier"]
            return h
        if kind == "shed":
            self.stats["sheds"] += 1
            return ServeDrop("shed", str(h.get("reason")), h.get("seq"))
        if kind != "frame" or len(parts) != 2:
            self.stats["drops"] += 1
            return ServeDrop("malformed",
                             f"unexpected answer type {kind!r} with "
                             f"{len(parts)} parts")
        blob = parts[1]
        try:
            # EVERY field the ViewerFrame needs is extracted here — a
            # corrupt-but-parseable header (missing/mistyped keys) must
            # surface as a typed drop, never an exception
            shape = tuple(int(x) for x in h["shape"])
            dtype = np.uint8 if h["dtype"] == "u8" else np.float32
            want = int(np.prod(shape)) * np.dtype(dtype).itemsize
            fidx, seq = int(h["frame"]), int(h["seq"])
            tier, stale, cached = (str(h["tier"]), bool(h["stale"]),
                                   bool(h["cached"]))
        except Exception:  # sitpu-lint: disable=SITPU-LEDGER (client-side typed drop, counted in stats)
            self.stats["drops"] += 1
            return ServeDrop("malformed", "bad frame header fields",
                             h.get("seq"))
        if h.get("crc") is not None and h["crc"] != zlib.crc32(blob):
            self.stats["drops"] += 1
            return ServeDrop("integrity", "answer blob checksum mismatch",
                             h.get("seq"))
        if len(blob) != want:
            self.stats["drops"] += 1
            return ServeDrop(
                "integrity", f"answer blob bytes ({len(blob)}) != "
                             f"declared shape ({want})", h.get("seq"))
        img = np.frombuffer(blob, dtype).reshape(shape)
        if dtype is np.uint8:
            img = img.astype(np.float32) / 255.0
        out = ViewerFrame(image=np.asarray(img, np.float32),
                          frame=fidx, seq=seq, tier=tier, stale=stale,
                          cached=cached, wire_bytes=len(blob))
        self.stats["answers"] += 1
        self.stats["bytes"] += len(blob)
        if out.cached:
            self.stats["cache_hits"] += 1
        if out.stale:
            self.stats["stale_answers"] += 1
        cam = self._cams.pop(out.seq, None)
        if cam is not None:
            self.last_camera = cam
        self.last = out
        return out

    def render(self, cam: Camera, timeout_ms: int = 5000
               ) -> Union[None, ServeDrop, ViewerFrame]:
        """Round trip: request ``cam`` and wait for ITS answer (earlier
        in-flight answers are consumed into ``last`` on the way)."""
        seq = self.request(cam)
        deadline = time.monotonic() + timeout_ms / 1000.0
        while time.monotonic() < deadline:
            left = max(0, int((deadline - time.monotonic()) * 1000))
            got = self.poll(timeout_ms=left)
            if got is None:
                return None
            if isinstance(got, ServeDrop):
                if got.seq in (None, seq):
                    return got
                continue
            if isinstance(got, ViewerFrame) and got.seq == seq:
                return got
        return None

    # ------------------------------------------------- local reprojection
    def render_local(self, cam: Camera) -> Optional[np.ndarray]:
        """Small-motion path between keyframes (ROADMAP item 4 play (c)):
        warp the last answered frame onto ``cam`` viewer-side — no round
        trip, no server cost. None until a first answer arrived."""
        if self.last is None or self.last_camera is None:
            return None
        from scenery_insitu_tpu.serve.reproject import reproject_planar

        return reproject_planar(self.last.image, self.last_camera, cam)

    def close(self) -> None:
        self.sock.close(linger=0)
