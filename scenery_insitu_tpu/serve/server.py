"""The VDI edge-serving tier: render once, serve thousands of viewers
(ROADMAP item 2; docs/SERVING.md).

The entire point of a VDI (PAPER.md §0) is view-independent
re-rendering: sim + march + composite cost is paid once per frame, and
any number of client cameras can be answered from the composited
representation. This module is the process that cashes that in —
≅ the reference's L7 streaming/steering plane (SURVEY §2, InSituMaster /
VideoEncoder), except the edge re-renders the REPRESENTATION per viewer
instead of rebroadcasting one camera's pixels.

`ViewerServer` subscribes to the composited VDI stream (tile-granular
and delta-aware — it rides the PR-11 `VDISubscriber`/`FrameAssembler`
substrate, so mid-stream joins, corrupt messages and P-frame resyncs are
typed drops, never exceptions) and answers N concurrent client cameras
per VDI frame by batching them into ONE device dispatch
(`ops.vdi_novel.render_vdi_batch`): one VDI fetch, one (lazy) proxy
expansion and one compiled program amortized across every viewer, with
padded buckets so the jit cache stays bounded. Around that core:

- per-client quality tiers — ``exact`` (closed-form renderer), ``proxy``
  (pre-shaded MXU proxy volume, built once per frame), ``wire`` (proxy
  pixels quantized to u8 wire precision, 4× fewer bytes per viewer);
- camera-delta caching — an unchanged camera (within ``serve.cam_tol``)
  on the same VDI frame re-serves the cached pixels without rendering;
- bounded staleness — answers from a VDI more than
  ``serve.staleness_frames`` behind the stream head are stamped
  ``stale`` (the viewer knows it is looking at the past);
- backpressure / admission control — viewers beyond
  ``serve.max_viewers`` and requests beyond ``serve.queue_cap`` get a
  typed ``shed`` answer; every shed, stale or degraded answer is minted
  on the obs ledger (``serve.*`` components, docs/OBSERVABILITY.md).

The client protocol (serve/client.py::`ViewerClient`) follows the
repo's zmq conventions — msgpack headers, CRC-validated blobs,
heartbeats — so the chaos harness (`testing/faults.py`) can exercise it
with the same injectors as every other seam.
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.obs.collector import lineage, trace_ctx
from scenery_insitu_tpu.obs.slo import SLOEngine
from scenery_insitu_tpu.config import (FaultConfig, FrameworkConfig,
                                       ServeConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.ops import slicer, vdi_novel
from scenery_insitu_tpu.runtime.streaming import (FrameAssembler,
                                                  StreamDrop,
                                                  VDISubscriber, _msgpack,
                                                  _zmq)

TIERS = ("exact", "proxy", "wire")


def camera_from_message(msg: dict) -> Camera:
    """Client camera payload -> Camera (the `make_camera_message` wire
    shape: eye/target/up lists + fov_y in radians, near/far optional).
    Raises on malformed payloads — the caller drops, typed."""
    import jax.numpy as jnp

    def vec3(key, default=None):
        v = msg[key] if key in msg else default
        a = np.asarray(v, np.float32)
        if a.shape != (3,) or not np.isfinite(a).all():
            raise ValueError(f"camera field {key!r} is not a finite vec3")
        return jnp.asarray(a)

    def scalar(key, default):
        x = float(msg.get(key, default))
        if not np.isfinite(x):
            raise ValueError(f"camera field {key!r} is not finite")
        return x

    # finite-but-degenerate values burn a full batched render producing
    # a garbage frame — refuse them with the rest of the validation
    fov_y = scalar("fov_y", float(np.deg2rad(50.0)))
    near = scalar("near", 0.1)
    far = scalar("far", 1000.0)
    if not 0.0 < fov_y < float(np.pi):
        raise ValueError(f"camera fov_y {fov_y} outside (0, pi)")
    if near <= 0.0 or far <= near:
        raise ValueError(f"camera clip range [{near}, {far}] is "
                         "degenerate (need 0 < near < far)")
    return Camera(eye=vec3("eye"),
                  target=vec3("target", (0.0, 0.0, 0.0)),
                  up=vec3("up", (0.0, 1.0, 0.0)),
                  fov_y=jnp.float32(fov_y),
                  near=jnp.float32(near), far=jnp.float32(far))


def _camera_sig(cam: Camera) -> np.ndarray:
    """Flattened camera leaves — the camera-delta cache key (compared
    with max-abs against ``serve.cam_tol``)."""
    return np.concatenate([np.ravel(np.asarray(x, np.float32))
                           for x in cam])


@dataclass
class _Client:
    ident: bytes
    tier: str
    last_seen: float
    # camera-delta cache: the last answered (adoption, tier, camera,
    # blob). cache_frame holds the server's monotone ADOPTION id, not
    # the stream frame index — indices restart with a publisher epoch,
    # and an old-epoch blob must never serve as the new frame. Tier
    # participates too (a re-negotiated tier changes the payload dtype).
    cache_frame: int = -1
    cache_tier: str = ""
    cache_sig: Optional[np.ndarray] = None
    cache_fields: Optional[dict] = None
    cache_blob: Optional[bytes] = None


@dataclass
class _Request:
    ident: bytes
    seq: int
    cam: Camera
    sig: np.ndarray
    regime: Tuple[int, int]
    t_in: float


class ViewerServer:
    """The edge-serving process: one upstream VDI subscription, one
    client-facing ROUTER socket, one batched render per tier bucket per
    frame. Single-threaded and pump-driven (`run_once` / `serve`) like
    the session loop — no hidden threads to leak under chaos."""

    def __init__(self, cfg: Optional[FrameworkConfig] = None, *,
                 connect: Optional[str] = None,
                 bind: Optional[str] = None,
                 fault: Optional[FaultConfig] = None):
        cfg = cfg or FrameworkConfig()
        self.cfg: ServeConfig = cfg.serve
        # cross-field check lives HERE, not in ServeConfig.__post_init__:
        # with_overrides applies one assignment at a time, so a
        # per-assignment cross-field check would make override ORDER
        # decide validity (buckets-then-batch_size raises, the reverse
        # passes) — only the final consumed pair can be judged
        if self.cfg.buckets[-1] < self.cfg.batch_size:
            raise ValueError(
                f"serve.buckets must reach serve.batch_size "
                f"({self.cfg.batch_size}); the ladder tops out at "
                f"{self.cfg.buckets[-1]}")
        self.fault = fault or cfg.fault
        # upstream liveness supervision stays OPT-IN (the PR-11
        # convention: without a heartbeat-pumping publisher a
        # healthy-but-slow stream would be torn down) — an explicit
        # fault= arg or serve.supervise_stream turns it on
        sub_fault = fault or (self.fault if self.cfg.supervise_stream
                              else None)
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        # bind the client socket BEFORE subscribing upstream: a bind
        # failure (address in use, the retry-loop case) must not leak a
        # SUB socket that keeps buffering full VDI frames to its HWM
        self.sock = self.ctx.socket(zmq.ROUTER)
        endpoint = bind or self.cfg.bind
        try:
            if endpoint.endswith(":0"):          # ephemeral port for tests
                port = self.sock.bind_to_random_port(endpoint[:-2])
                self.endpoint = (
                    f"{endpoint[:-2].replace('*', '127.0.0.1')}:{port}")
            else:
                self.sock.bind(endpoint)
                self.endpoint = endpoint.replace("*", "127.0.0.1")
        except Exception:
            self.sock.close(linger=0)
            raise
        self.sub = VDISubscriber(connect or self.cfg.connect,
                                 fault=sub_fault)
        self.asm = FrameAssembler(fault=self.fault)
        self.clients: Dict[bytes, _Client] = {}
        # pending camera requests, latest-wins per client (an interactive
        # viewer's stale pose is worthless once a newer one arrived)
        self.queue: "OrderedDict[bytes, _Request]" = OrderedDict()
        # current frame state (adopted whole frames only)
        self.frame: Optional[dict] = None
        self.newest: Optional[int] = None    # newest stream index STARTED
        self._epoch = self.sub.last_epoch    # publisher incarnation seen
        self._adoption = 0         # monotone id of the adopted frame —
        #                            the cache key (stream INDICES restart
        #                            with a publisher epoch, this never does)
        self._frame_orphaned = False         # frame predates an epoch change
        self._proxy = None                   # per-frame lazy proxy volume
        self._jit: Dict[tuple, object] = {}
        self._spec_new: Dict[tuple, object] = {}
        self.stats = {"frames_adopted": 0, "answers": 0, "cache_hits": 0,
                      "sheds": 0, "stale_answers": 0, "batches": 0,
                      "batch_cameras": 0, "client_drops": 0,
                      "evictions": 0, "coalesced": 0, "proxy_builds": 0,
                      "stream_drops": 0}
        # live SLO checks on the answer path (docs/OBSERVABILITY.md
        # "SLO engine"): camera-to-pixel latency + answer staleness
        self.slo = SLOEngine(cfg.slo)

    # ------------------------------------------------------------ stream
    def pump_stream(self, timeout_ms: int = 0,
                    max_messages: int = 64) -> int:
        """Drain the upstream VDI stream (first receive may wait
        ``timeout_ms``; the rest are non-blocking). Tile messages
        assemble; complete frames are adopted. Returns frames adopted."""
        adopted = 0
        for _ in range(max_messages):
            got = self.sub.receive_tile(timeout_ms=timeout_ms)
            timeout_ms = 0
            if self.sub.last_epoch != self._epoch:
                # publisher restarted: its frame indices restart too, so
                # the server's OWN assembler and stream-head tracking
                # must reset with it (the subscriber resets its internal
                # state; without this mirror, the late-tile guard wedges
                # assembly and every answer reads as stale forever)
                self._epoch = self.sub.last_epoch
                self.asm = FrameAssembler(fault=self.fault)
                self.newest = None
                # the retained frame is the DEAD incarnation's last one;
                # until the new stream completes a frame, answers from
                # it must read stale (its age vs the new head is
                # meaningless, not zero)
                self._frame_orphaned = self.frame is not None
            if got is None:
                break
            if isinstance(got, StreamDrop):
                # already ledgered by the subscriber (stream.integrity /
                # stream.gap / stream.delta_resync) — count and go on.
                # A refused frame still STARTED: during a resync window
                # every P/SKIP record surfaces here, and if the head
                # froze too, answers from the retained frame would read
                # stale=False for the whole degraded stretch — exactly
                # when the bounded-staleness contract matters most
                if got.frame is not None \
                        and got.epoch == self.sub.last_epoch:
                    self.newest = got.frame if self.newest is None \
                        else max(self.newest, got.frame)
                self.stats["stream_drops"] += 1
                continue
            vdi, meta, tile = got
            idx = int(np.asarray(meta.index))
            self.newest = idx if self.newest is None \
                else max(self.newest, idx)
            out = self.asm.add(vdi, meta, tile)
            if out is not None:
                self._adopt(*out)
                adopted += 1
        return adopted

    def _adopt(self, vdi: VDI, meta: VDIMetadata) -> None:
        import jax
        import jax.numpy as jnp

        mdt = "bf16" if jax.default_backend() == "tpu" else "f32"
        spec0 = vdi_novel.axis_spec_from_meta(meta, matmul_dtype=mdt)
        axcam0 = vdi_novel.axis_camera_from_meta(meta, spec0)
        ns = self.cfg.num_slices or None
        if ns is None:
            # derive the plane count from the frame's OWN depth range
            # (the render_vdi_exact s_cap logic): the reconstructed
            # ladder starts at the generating camera's near plane, and
            # for gather-engine VDIs that near plane sits well before
            # the volume — a fixed in-plane heuristic would stop
            # marching before the content. Quantized up so the jit key
            # only changes when the content depth moves materially.
            ends = np.asarray(vdi.depth)[:, 1]
            len0 = np.maximum(np.asarray(axcam0.ray_lengths()), 1e-6)
            s_end = np.where(np.isfinite(ends), ends, 0.0) / len0[None]
            smax = max(1.0, float(s_end.max()))
            ds0 = abs(float(np.asarray(axcam0.dwm))) \
                / max(float(np.asarray(axcam0.zp)), 1e-6)
            raw = int(np.ceil((smax - 1.0) / max(ds0, 1e-6))) + 2
            ns = max(16, -(-raw // 16) * 16)
        # the ONE device fetch of the frame, shared by every viewer
        self.frame = {
            "vdi": VDI(jnp.asarray(np.asarray(vdi.color)),
                       jnp.asarray(np.asarray(vdi.depth))),
            "meta": meta, "index": int(np.asarray(meta.index)),
            "spec0": spec0, "axcam0": axcam0, "num_slices": ns,
        }
        self._proxy = None
        self._adoption += 1
        self._frame_orphaned = False
        # bound the compiled-program caches: the derived num_slices (and
        # with it the proxy shape) tracks the content depth, so a long
        # drifting run would otherwise leak one executable set per
        # 16-slice step — past the cap, drop everything and recompile
        # for the live shapes only
        if len(self._jit) > 32:
            self._jit.clear()
            self._spec_new.clear()
        self.stats["frames_adopted"] += 1
        _obs.get_recorder().count("serve_frames_adopted")

    # ----------------------------------------------------------- clients
    def _drop_client(self, why: str) -> None:
        """``why`` must be a CONSTANT string: it lands in the ledger's
        dedup key, and client-controlled variability there (a payload
        repr, an unknown type name) lets one hostile peer grow the
        process-global ledger without bound (the PR-11 subscriber
        convention — fixed ledger reasons, variable detail stays out)."""
        self.stats["client_drops"] += 1
        _obs.get_recorder().count("serve_client_drops")
        _obs.degrade("serve.client", "client message", "dropped", why,
                     warn=False)

    def _shed(self, ident: bytes, seq: Optional[int], why: str) -> None:
        self.stats["sheds"] += 1
        _obs.get_recorder().count("serve_sheds")
        _obs.degrade(
            "serve.shed", "viewer request", "shed",
            f"admission control: the {why} cap is reached; the client "
            "got a typed shed answer", warn=False)
        self.sock.send_multipart([ident, _msgpack().packb(
            {"type": "shed", "reason": why, "seq": seq})])

    def _resolve_tier(self, tier) -> str:
        if tier in TIERS:
            return tier
        _obs.degrade(
            "serve.tier", "requested tier", self.cfg.default_tier,
            "client requested an unknown quality tier; the configured "
            "default renders instead", warn=False)
        return self.cfg.default_tier

    def _admit(self, ident: bytes, msg: dict, now: float
               ) -> Optional[_Client]:
        """Look up (refreshing liveness) or admit a client at the
        default tier; None — after a typed shed — when the max_viewers
        cap refuses a new ident."""
        cl = self.clients.get(ident)
        if cl is not None:
            cl.last_seen = now
            return cl
        if len(self.clients) >= self.cfg.max_viewers:
            self._shed(ident, msg.get("seq"), "max_viewers")
            return None
        cl = _Client(ident, self.cfg.default_tier, now)
        self.clients[ident] = cl
        return cl

    def _hello(self, ident: bytes, msg: dict, now: float) -> None:
        fresh = ident not in self.clients
        cl = self._admit(ident, msg, now)
        if cl is None:
            return
        if fresh or "tier" in msg:
            cl.tier = self._resolve_tier(
                msg.get("tier", self.cfg.default_tier))
        self.sock.send_multipart([ident, _msgpack().packb(
            {"type": "welcome", "tier": cl.tier,
             "width": self.cfg.width, "height": self.cfg.height,
             "frame": -1 if self.frame is None else self.frame["index"]})])

    def _camera(self, ident: bytes, msg: dict, now: float) -> None:
        # validate BEFORE admission: a sender of garbage must not
        # occupy a max_viewers slot (up to client_timeout_s, renewable)
        # that it never earned with a renderable request
        try:
            cam = camera_from_message(msg)
            seq = int(msg.get("seq", 0))
        except Exception:  # sitpu-lint: disable=SITPU-LEDGER (mints via _drop_client)
            self._drop_client("camera payload failed validation")
            return
        # implicit hello — still through admission; a tier carried on
        # the request is honored (a viewer that never said hello must
        # not be silently downgraded to serve.default_tier)
        cl = self._admit(ident, msg, now)
        if cl is None:
            return
        tier = msg.get("tier")
        if tier is not None and tier != cl.tier:
            cl.tier = self._resolve_tier(tier)
        if ident not in self.queue and len(self.queue) >= self.cfg.queue_cap:
            self._shed(ident, seq, "queue_cap")
            return
        if ident in self.queue:
            self.stats["coalesced"] += 1
            _obs.get_recorder().count("serve_requests_coalesced")
        self.queue[ident] = _Request(ident, seq, cam, _camera_sig(cam),
                                     slicer.choose_axis(cam), now)
        _obs.get_recorder().count("serve_requests")

    def pump_clients(self, max_messages: int = 256) -> int:
        """Drain the client socket: hellos, camera requests, byes,
        heartbeats. Malformed/oversized messages drop typed
        (``serve.client``); silent clients past ``client_timeout_s`` are
        evicted. Returns messages consumed."""
        zmq = _zmq()
        n = 0
        for _ in range(max_messages):
            try:
                parts = self.sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                break
            n += 1
            if len(parts) != 2:
                self._drop_client("unexpected [ident, payload] framing")
                continue
            ident, raw = parts
            if len(raw) > self.fault.max_message_bytes:
                self._drop_client("message exceeds fault.max_message_bytes")
                continue
            try:
                msg = _msgpack().unpackb(raw)
            except Exception:  # sitpu-lint: disable=SITPU-LEDGER (mints via _drop_client)
                self._drop_client("unparseable msgpack from a viewer")
                continue
            if not isinstance(msg, dict):
                self._drop_client("client payload is not a map")
                continue
            now = time.monotonic()
            if msg.get("hb"):
                cl = self.clients.get(ident)
                if cl is not None:
                    cl.last_seen = now
                continue
            kind = msg.get("type")
            if kind == "hello":
                self._hello(ident, msg, now)
            elif kind == "camera":
                self._camera(ident, msg, now)
            elif kind == "bye":
                self.clients.pop(ident, None)
                self.queue.pop(ident, None)
            else:
                self._drop_client("unknown client message type")
        self._evict(time.monotonic())
        return n

    def _evict(self, now: float) -> None:
        for ident, cl in list(self.clients.items()):
            if now - cl.last_seen > self.cfg.client_timeout_s:
                del self.clients[ident]
                self.queue.pop(ident, None)
                self.stats["evictions"] += 1
                _obs.get_recorder().count("serve_clients_evicted")

    # ------------------------------------------------------------ render
    def _spec_new_for(self, regime: Tuple[int, int], shape: tuple):
        key = (regime, shape)
        spec = self._spec_new.get(key)
        if spec is None:
            from scenery_insitu_tpu.config import SliceMarchConfig

            cfg = SliceMarchConfig(
                matmul_dtype=self.frame["spec0"].matmul_dtype,
                scale=self.cfg.march_scale)
            # cam is unused when axis_sign is given; any concrete one does
            spec = slicer.make_spec(Camera.create((0.0, 0.0, 3.0)), shape,
                                    cfg, axis_sign=regime)
            self._spec_new[key] = spec
        return spec

    def _ensure_proxy(self):
        if self._proxy is not None:
            return self._proxy
        import jax

        spec0 = self.frame["spec0"]
        ns = self.frame["num_slices"]
        key = ("build", spec0, ns)
        fn = self._jit.get(key)
        if fn is None:
            fn = jax.jit(lambda c, d, axcam: vdi_novel.vdi_to_rgba_volume(
                VDI(c, d), axcam, spec0, num_slices=ns))
            self._jit[key] = fn
        vdi = self.frame["vdi"]
        with _obs.get_recorder().span("serve_proxy_build",
                                      frame=self.frame["index"]):
            self._proxy = fn(vdi.color, vdi.depth, self.frame["axcam0"])
            jax.block_until_ready(self._proxy.data)
        self.stats["proxy_builds"] += 1
        _obs.get_recorder().count("serve_proxy_builds")
        return self._proxy

    def _render_fn(self, tier: str, regime: Optional[Tuple[int, int]],
                   bucket: int, proxy_shape: Optional[tuple]):
        import jax

        spec0 = self.frame["spec0"]
        w, h = self.cfg.width, self.cfg.height
        key = (tier, regime, bucket, spec0, proxy_shape, w, h)
        fn = self._jit.get(key)
        if fn is not None:
            return fn
        if tier == "exact":
            fn = jax.jit(lambda c, d, axcam, cams:
                         vdi_novel.render_vdi_batch(
                             VDI(c, d), axcam, spec0, cams, w, h,
                             tier="exact"))
        else:
            from scenery_insitu_tpu.core.volume import Volume

            spec_new = self._spec_new_for(regime, proxy_shape)
            fn = jax.jit(lambda pd, po, ps, cams:
                         vdi_novel.render_vdi_batch(
                             None, None, spec0, cams, w, h, tier="proxy",
                             proxy=Volume(pd, po, ps), spec_new=spec_new))
        self._jit[key] = fn
        return fn

    def _bucket(self, n: int) -> int:
        for b in self.cfg.buckets:
            if b >= n:
                return b
        return self.cfg.buckets[-1]

    def answer_pending(self) -> int:
        """Answer every queued request against the current VDI frame:
        camera-delta cache hits first, then one batched dispatch per
        (tier, regime) bucket. Returns answers sent."""
        if self.frame is None or not self.queue:
            return 0
        import jax

        fidx = self.frame["index"]
        stale = self._frame_orphaned or (
            self.newest is not None
            and self.newest - fidx > self.cfg.staleness_frames)
        if stale:
            _obs.degrade(
                "serve.stale", "fresh frame", "stale answer",
                "the served VDI is more than serve.staleness_frames "
                "behind the stream head; answers are stamped stale",
                warn=False)
        served = 0
        groups: Dict[tuple, List[_Request]] = {}
        for ident, req in list(self.queue.items()):
            del self.queue[ident]
            cl = self.clients.get(ident)
            if cl is None:
                continue
            if (cl.cache_blob is not None
                    and cl.cache_frame == self._adoption
                    and cl.cache_tier == cl.tier
                    and cl.cache_sig is not None
                    and cl.cache_sig.shape == req.sig.shape
                    and float(np.max(np.abs(req.sig - cl.cache_sig)))
                    <= self.cfg.cam_tol):
                # staleness is re-stamped: the cached PIXELS are still
                # the current frame's (cache_frame == fidx), but the
                # stream head may have moved past it since they were
                # rendered — a frozen stale=False would break the
                # bounded-staleness contract
                fields = dict(cl.cache_fields, seq=req.seq, cached=True,
                              stale=bool(stale),
                              tc=trace_ctx(fidx,
                                           _obs.get_recorder().rank))
                self.sock.send_multipart(
                    [ident, _msgpack().packb(fields), cl.cache_blob])
                self.stats["cache_hits"] += 1
                self.stats["answers"] += 1
                _obs.get_recorder().count("serve_cache_hits")
                _obs.get_recorder().count("serve_answers")
                _obs.get_recorder().count("serve_bytes_out",
                                          len(cl.cache_blob))
                if stale:
                    self.stats["stale_answers"] += 1
                    _obs.get_recorder().count("serve_stale_answers")
                self._observe_answer(req, fidx, stale)
                served += 1
                continue
            gkey = ("exact", None) if cl.tier == "exact" \
                else ("proxy", req.regime)
            groups.setdefault(gkey, []).append(req)
        vdi = self.frame["vdi"]
        for (gtier, regime), reqs in groups.items():
            for lo in range(0, len(reqs), self.cfg.batch_size):
                chunk = reqs[lo:lo + self.cfg.batch_size]
                bucket = self._bucket(len(chunk))
                cams = [r.cam for r in chunk]
                cams += [chunk[-1].cam] * (bucket - len(chunk))
                stacked = vdi_novel.stack_cameras(cams)
                with _obs.get_recorder().span(
                        "serve_batch", frame=fidx, tier=gtier,
                        cameras=len(chunk), bucket=bucket):
                    if gtier == "exact":
                        fn = self._render_fn("exact", None, bucket, None)
                        imgs = fn(vdi.color, vdi.depth,
                                  self.frame["axcam0"], stacked)
                    else:
                        proxy = self._ensure_proxy()
                        fn = self._render_fn("proxy", regime, bucket,
                                             tuple(proxy.data.shape[-3:]))
                        imgs = fn(proxy.data, proxy.origin, proxy.spacing,
                                  stacked)
                    imgs = np.asarray(jax.block_until_ready(imgs))
                self.stats["batches"] += 1
                self.stats["batch_cameras"] += len(chunk)
                _obs.get_recorder().count("serve_batches")
                _obs.get_recorder().count("serve_batch_cameras",
                                          len(chunk))
                for i, req in enumerate(chunk):
                    self._reply(req, imgs[i], fidx, stale)
                    served += 1
        return served

    def _observe_answer(self, req: _Request, fidx: int,
                        stale: bool) -> None:
        """Per-answer telemetry: camera-to-pixel latency and answer
        staleness feed the SLO engine; one ``serve`` lineage hop joins
        the frame's fleet-trace arc."""
        c2p_ms = (time.monotonic() - req.t_in) * 1e3
        self.slo.observe("camera_to_pixel_ms", c2p_ms, frame=fidx)
        if self.newest is not None:
            self.slo.observe("staleness_frames",
                             max(0, self.newest - fidx), frame=fidx)
        lineage("serve", "send", fidx, seq=req.seq, stale=bool(stale),
                cam_to_pix_ms=round(c2p_ms, 3))

    def _reply(self, req: _Request, img: np.ndarray, fidx: int,
               stale: bool) -> None:
        cl = self.clients.get(req.ident)
        tier = cl.tier if cl is not None else self.cfg.default_tier
        if tier == "wire":
            # wire-precision tier: u8 unorm pixels, 4x fewer bytes/viewer
            payload = np.clip(np.round(img * 255.0), 0, 255) \
                .astype(np.uint8)
            dtype = "u8"
        else:
            payload = np.ascontiguousarray(img, np.float32)
            dtype = "f32"
        blob = payload.tobytes()
        fields = {"type": "frame", "frame": fidx, "seq": req.seq,
                  "tier": tier, "stale": bool(stale), "cached": False,
                  "shape": list(payload.shape), "dtype": dtype,
                  "crc": zlib.crc32(blob),
                  "tc": trace_ctx(fidx, _obs.get_recorder().rank)}
        self.sock.send_multipart([req.ident, _msgpack().packb(fields),
                                  blob])
        self.stats["answers"] += 1
        rec = _obs.get_recorder()
        rec.count("serve_answers")
        rec.count("serve_bytes_out", len(blob))
        if stale:
            self.stats["stale_answers"] += 1
            rec.count("serve_stale_answers")
        self._observe_answer(req, fidx, stale)
        if cl is not None:
            cl.cache_frame = self._adoption
            cl.cache_tier = tier
            cl.cache_sig = req.sig
            cl.cache_fields = dict(fields, cached=True)
            cl.cache_blob = blob

    # -------------------------------------------------------------- loop
    def run_once(self, timeout_ms: int = 50) -> int:
        """One pump: drain clients, drain stream, answer pending.
        Clients drain FIRST, and the stream wait is skipped while there
        are requests the server can actually answer — otherwise an idle
        stream puts a ``timeout_ms`` latency floor under every
        camera-to-pixel answer. Requests queued BEFORE the first frame
        arrives don't skip the wait (nothing is answerable yet, and a
        zero-wait pump would busy-spin until the stream starts).
        Returns answers sent."""
        self.pump_clients()
        answerable = bool(self.queue) and self.frame is not None
        self.pump_stream(timeout_ms=0 if answerable else timeout_ms)
        return self.answer_pending()

    def serve(self, seconds: Optional[float] = None,
              max_answers: Optional[int] = None) -> dict:
        """Pump until ``seconds`` elapse or ``max_answers`` were sent
        (None = forever on that axis); returns the stats snapshot."""
        deadline = None if seconds is None else time.monotonic() + seconds
        answers = 0
        try:
            while (deadline is None or time.monotonic() < deadline) and \
                    (max_answers is None or answers < max_answers):
                answers += self.run_once(timeout_ms=20)
        except BaseException:
            # flight recorder: the serve loop died — dump the recorder's
            # last window before the exception erases it
            _obs.flight_flush(where="serve")
            raise
        return dict(self.stats)

    def slo_snapshot(self) -> dict:
        """The SLO engine's machine-readable health record for THIS
        edge (camera-to-pixel + staleness quantiles vs budget)."""
        return self.slo.snapshot()

    def close(self) -> None:
        self.sock.close(linger=0)
        self.sub.close()
