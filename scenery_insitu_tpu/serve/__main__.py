"""``python -m scenery_insitu_tpu.serve`` — run the VDI edge-serving
process (docs/SERVING.md).

Pair with any VDI publisher, e.g.::

    python examples/insitu_grayscott.py --publish &
    python -m scenery_insitu_tpu.serve --connect tcp://localhost:6655 \
        --bind 'tcp://*:6657'

then point `ViewerClient` (or several) at the bind address.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="VDI edge server: subscribe to a composited VDI "
                    "stream, answer N client cameras per frame from one "
                    "batched render (docs/SERVING.md)")
    ap.add_argument("--connect", default=None,
                    help="upstream VDI stream (default serve.connect)")
    ap.add_argument("--bind", default=None,
                    help="client-facing endpoint (default serve.bind)")
    ap.add_argument("--seconds", type=float, default=0.0,
                    help="serve this long then exit (0 = forever)")
    ap.add_argument("--stats-every", type=float, default=10.0,
                    help="seconds between stats lines")
    ap.add_argument("-o", "--override", action="append", default=[],
                    help="config override, e.g. serve.max_viewers=128 "
                         "(repeatable)")
    args = ap.parse_args(argv)

    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.serve.server import ViewerServer

    cfg = FrameworkConfig.load(overrides=tuple(args.override))
    srv = ViewerServer(cfg, connect=args.connect, bind=args.bind)
    print(f"serving on {srv.endpoint} (upstream "
          f"{args.connect or cfg.serve.connect}, tiers exact/proxy/wire, "
          f"max_viewers={cfg.serve.max_viewers})", flush=True)
    deadline = None if args.seconds <= 0 else time.monotonic() + args.seconds
    next_stats = time.monotonic() + args.stats_every
    try:
        while deadline is None or time.monotonic() < deadline:
            srv.run_once(timeout_ms=50)
            if time.monotonic() >= next_stats:
                print(json.dumps({"clients": len(srv.clients),
                                  **srv.stats}), flush=True)
                next_stats = time.monotonic() + args.stats_every
    except KeyboardInterrupt:
        pass
    finally:
        print(json.dumps({"final": True, "clients": len(srv.clients),
                          **srv.stats}), file=sys.stdout, flush=True)
        srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
