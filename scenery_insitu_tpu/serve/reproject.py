"""Viewer-side reprojection between VDI keyframes (ROADMAP item 4 play
(c); docs/SERVING.md "Local reprojection").

Between two server answers, a small camera move does not need a round
trip: the classic VDI trick (PAPER.md — the representation is
view-independent, so the VIEW side owns small-motion latency) is to warp
the last rendered image onto the new camera through a proxy surface.
Here the proxy is the plane through the old camera's look-at target,
perpendicular to its view direction — exact for content on that plane,
a parallax-free approximation elsewhere, and always bounded by the next
keyframe (the server answer replaces the warp wholesale).

Pure numpy, host-side: this runs in the viewer process per displayed
frame, not on the render tier.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from scenery_insitu_tpu.core.camera import (Camera, pixel_rays,
                                            projection_matrix, view_matrix,
                                            world_to_ndc)


def reproject_planar(img: np.ndarray, cam_from: Camera, cam_to: Camera,
                     plane_point: Optional[np.ndarray] = None,
                     plane_normal: Optional[np.ndarray] = None,
                     background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)
                     ) -> np.ndarray:
    """Inverse-warp ``img`` (f32[4, H, W] premultiplied, rendered from
    ``cam_from``) onto ``cam_to``'s pixels: each new pixel's ray is
    intersected with the proxy plane, the hit projected back through the
    OLD camera, and the old image bilinearly sampled there. Pixels whose
    ray misses the plane (behind the eye / parallel) or lands outside
    the old frame get ``background``. ``cam_to == cam_from`` is the
    identity up to bilinear epsilon."""
    img = np.asarray(img, np.float32)
    _, h, w = img.shape
    eye_from = np.asarray(cam_from.eye, np.float64)
    target_from = np.asarray(cam_from.target, np.float64)
    p0 = (target_from if plane_point is None
          else np.asarray(plane_point, np.float64))
    n = ((p0 - eye_from) if plane_normal is None
         else np.asarray(plane_normal, np.float64))
    n = n / max(float(np.linalg.norm(n)), 1e-12)

    origin, dirs = pixel_rays(cam_to, w, h)
    origin = np.asarray(origin, np.float64)             # [3]
    dirs = np.asarray(dirs, np.float64)                 # [3, H, W]
    denom = np.einsum("c,chw->hw", n, dirs)
    safe = np.where(np.abs(denom) < 1e-9, 1e-9, denom)
    t = float(np.dot(n, p0 - origin)) / safe            # [H, W]
    valid = (np.abs(denom) >= 1e-9) & (t > 0.0)
    world = origin[:, None, None] + t[None] * dirs      # [3, H, W]

    view = np.asarray(view_matrix(cam_from), np.float64)
    proj = np.asarray(projection_matrix(cam_from, w, h), np.float64)
    ndc = np.asarray(world_to_ndc(
        np.moveaxis(world, 0, -1).astype(np.float32), view.astype(np.float32),
        proj.astype(np.float32)))                       # [H, W, 3]
    px = (ndc[..., 0] + 1.0) * 0.5 * w - 0.5
    py = (1.0 - ndc[..., 1]) * 0.5 * h - 0.5

    x0 = np.floor(px).astype(np.int64)
    y0 = np.floor(py).astype(np.int64)
    fx = (px - x0).astype(np.float32)
    fy = (py - y0).astype(np.float32)
    inside = valid & (px >= -0.5) & (px <= w - 0.5) \
        & (py >= -0.5) & (py <= h - 0.5)

    def tap(yy, xx):
        oob = (xx < 0) | (xx >= w) | (yy < 0) | (yy >= h)
        s = img[:, np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)]
        return np.where(oob[None], 0.0, s)

    out = ((1 - fx) * (1 - fy))[None] * tap(y0, x0) \
        + (fx * (1 - fy))[None] * tap(y0, x0 + 1) \
        + ((1 - fx) * fy)[None] * tap(y0 + 1, x0) \
        + (fx * fy)[None] * tap(y0 + 1, x0 + 1)
    bg = np.asarray(background, np.float32).reshape(4, 1, 1)
    return np.where(inside[None], out, bg).astype(np.float32)
