from scenery_insitu_tpu.models.pipelines import (  # noqa: F401
    grayscott_vdi_frame_step, lj_particle_frame_step)
