"""Composed end-to-end pipelines ("model families"): the canonical frame
steps that bench.py, __graft_entry__.py and the session loop all share, so
the benchmark measures exactly the path that is compiled-checked and tested.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction, for_dataset
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.obs.profiler import phase as _phase
from scenery_insitu_tpu.ops.composite import composite_vdis
from scenery_insitu_tpu.ops.splat import speed_colors, splat_particles
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
from scenery_insitu_tpu.sim import grayscott as gs
from scenery_insitu_tpu.sim import particles as pt


def resolve_occupancy_cfg(slicer_cfg, occupancy: Optional[str]):
    """Normalize an empty-space-skipping mode name onto a
    SliceMarchConfig (docs/PERF.md "Empty-space skipping"). ONE resolver
    shared by `grayscott_vdi_frame_step` and bench.py's SITPU_BENCH_SKIP
    reporting, so the artifact's recorded march config can never drift
    from the march actually benched. ``None`` keeps the config as-is."""
    import dataclasses

    if occupancy is None:
        return slicer_cfg
    if occupancy not in ("off", "chunk", "pyramid", "sim"):
        raise ValueError(f"occupancy must be 'off', 'chunk', 'pyramid' "
                         f"or 'sim', got {occupancy!r}")
    if occupancy == "off":
        return dataclasses.replace(slicer_cfg, skip_empty=False)
    if occupancy == "chunk":
        return dataclasses.replace(slicer_cfg, skip_empty=True,
                                   occupancy_vtiles=0)
    # pyramid / sim want the in-plane level on
    from scenery_insitu_tpu.config import OCCUPANCY_VTILES_DEFAULT

    vt = slicer_cfg.occupancy_vtiles
    return dataclasses.replace(
        slicer_cfg, skip_empty=True,
        occupancy_vtiles=(OCCUPANCY_VTILES_DEFAULT if vt <= 0 else vt))


def grayscott_vdi_frame_step(width: int, height: int,
                             sim_steps: int = 5, max_steps: int = 96,
                             vdi_cfg: Optional[VDIConfig] = None,
                             comp_cfg: Optional[CompositeConfig] = None,
                             tf: Optional[TransferFunction] = None,
                             params: Optional[gs.GrayScottParams] = None,
                             fov_y_deg: float = 50.0,
                             engine: str = "auto",
                             grid_shape=None, axis_sign=None,
                             slicer_cfg=None,
                             render_dtype: Optional[str] = None,
                             sim_fused: bool = True,
                             occupancy: Optional[str] = None):
    """Single-chip in-situ frame step: Gray-Scott advance → VDI generation
    → composite. Returns ``fn(u, v, eye) -> (color, depth, u, v)``
    (jittable; the flagship single-device hot path).

    ``render_dtype`` (None = ``slicer_cfg.render_dtype``): "bf16" marches
    a bf16 copy of the density volume (storage only — accumulation stays
    f32; see SliceMarchConfig.render_dtype). ``sim_fused=False`` pins the
    sim advance to the XLA roll formulation instead of the time-fused
    Pallas stencil — the sim-fusion lever's A/B switch.

    ``occupancy`` picks the empty-space-skipping mode of the A/B ladder
    (benchmarks/occupancy_bench.py; docs/PERF.md "Empty-space
    skipping"); None keeps whatever ``slicer_cfg`` says:
      "off"      no skipping (the baseline);
      "chunk"    whole-chunk skipping only (vtiles=0);
      "pyramid"  chunk × in-plane-tile pyramid rebuilt from the volume
                 each frame (vtiles stays as configured, defaulting 16);
      "sim"      the pyramid is built from per-brick field ranges that
                 ride out of the sim advance itself
                 (grayscott.multi_step_fast_ranges →
                 occupancy.pyramid_from_ranges) — conservative, zero
                 extra volume traffic; mxu-only.

    engine="mxu" uses the slice-march raycaster (ops/slicer.py; requires
    the static ``grid_shape`` AND ``axis_sign`` — the march regime, from
    ``slicer.choose_axis(camera)`` on a representative camera. Eyes the
    returned step is called with must stay inside that regime (within 45°
    of the axis); build one step per regime otherwise). The VDI then lives
    on the virtual axis camera's grid instead of (width, height). "auto"
    resolves to mxu on TPU, gather elsewhere.

    With ``vdi_cfg.adaptive_mode == "temporal"`` (mxu only) the step
    signature gains carried threshold state:
    ``fn(u, v, eye, thr) -> (color, depth, u, v, thr')`` — seed thr with
    the returned function's ``init_threshold(u, v, eye)`` attribute (one
    jittable histogram counting march), then thread it through the frame
    loop (one march per frame instead of two; see
    slicer.generate_vdi_mxu_temporal)."""
    import dataclasses

    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer

    tf = tf or for_dataset("gray_scott")
    vdi_cfg = vdi_cfg or VDIConfig(max_supersegments=8, adaptive_iters=2)
    comp_cfg = comp_cfg or CompositeConfig(max_output_supersegments=8,
                                           adaptive_iters=2)
    params = params or gs.GrayScottParams.create()
    engine = slicer.resolve_engine(engine)
    slicer_cfg = slicer_cfg or SliceMarchConfig()
    sim_occ = occupancy == "sim"
    slicer_cfg = resolve_occupancy_cfg(slicer_cfg, occupancy)
    if sim_occ and engine != "mxu":
        raise ValueError("occupancy='sim' feeds the slice march's "
                         "pyramid; it needs engine='mxu'")
    if render_dtype is None:
        render_dtype = slicer_cfg.render_dtype
    else:
        # keep the spec in lockstep with the explicit override so
        # permute_volume and the pre-cast field copy below agree
        slicer_cfg = dataclasses.replace(slicer_cfg,
                                         render_dtype=render_dtype)

    spec = None
    if engine == "mxu":
        if grid_shape is None:
            raise ValueError("engine='mxu' needs the static grid_shape")
        if axis_sign is None:
            raise ValueError(
                "engine='mxu' needs axis_sign — pass "
                "slicer.choose_axis(cam) for a camera representative of "
                "the eyes this step will be called with")
        spec = slicer.make_spec(
            Camera.create((0.0, 0.6, 3.0), fov_y_deg=fov_y_deg),
            tuple(grid_shape), slicer_cfg, axis_sign=axis_sign)

    temporal = vdi_cfg.adaptive and vdi_cfg.adaptive_mode == "temporal"
    if temporal and engine != "mxu":
        raise ValueError("adaptive_mode='temporal' needs engine='mxu'")
    if render_dtype not in ("f32", "bf16"):
        raise ValueError(f"render_dtype must be 'f32' or 'bf16', "
                         f"got {render_dtype!r}")
    # the 1024^3 memory plan: SIM state stays f32 (bf16 storage loses the
    # ~1e-3 per-step reaction increments against values near 1.0 and the
    # pattern stalls), but the RENDERED copy of the field can be bf16 —
    # the march's permuted volume halves to ~2.1 GB at 1024^3 and the
    # resampling einsum was casting to bf16 anyway (matmul_dtype)
    rdt = jnp.bfloat16 if render_dtype == "bf16" else None
    advance = gs.multi_step_fast if sim_fused else gs.multi_step

    def frame_step(u, v, eye, thr=None):
        if temporal and thr is None:
            raise ValueError(
                "temporal mode carries threshold state: call as "
                "frame_step(u, v, eye, thr), seeding thr with "
                "frame_step.init_threshold(u, v, eye)")
        if sim_occ:
            # the occupancy structure rides out of the sim advance
            # (fused-kernel epilogue, lax fallback ledgered) — the
            # render below never re-reads the volume for it
            with _phase("sim_step"):
                state, rng = gs.multi_step_fast_ranges(
                    gs.GrayScott(u, v, params), sim_steps,
                    fused=sim_fused)
        else:
            with _phase("sim_step"):
                state = advance(gs.GrayScott(u, v, params), sim_steps)
        field = state.field if rdt is None else state.field.astype(rdt)
        vol = Volume.centered(field, extent=2.0)
        occ_pyr = None
        if sim_occ:
            from scenery_insitu_tpu.ops import occupancy as occ_mod

            occ_pyr = occ_mod.pyramid_from_ranges(rng, vol, tf, spec)
        cam = Camera.create(eye, fov_y_deg=fov_y_deg, near=0.5, far=20.0)
        with _phase("march"):
            if temporal:
                vdi, _, _, thr = slicer.generate_vdi_mxu_temporal(
                    vol, tf, cam, spec, thr, vdi_cfg, occupancy=occ_pyr)
            elif engine == "mxu":
                vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec,
                                                    vdi_cfg,
                                                    occupancy=occ_pyr)
            else:
                vdi, _ = generate_vdi(vol, tf, cam, width, height,
                                      vdi_cfg, max_steps=max_steps)
        with _phase("merge"):
            out = composite_vdis(vdi.color[None], vdi.depth[None],
                                 comp_cfg)
        if temporal:
            return out.color, out.depth, state.u, state.v, thr
        return out.color, out.depth, state.u, state.v

    if temporal:
        def init_threshold(u, v, eye):
            """Jittable seed for the carried threshold state (one
            histogram counting march on the current sim state)."""
            vol = Volume.centered(gs.GrayScott(u, v, params).field,
                                  extent=2.0)
            cam = Camera.create(eye, fov_y_deg=fov_y_deg, near=0.5,
                                far=20.0)
            return slicer.initial_threshold(vol, tf, cam, spec, vdi_cfg)

        frame_step.init_threshold = init_threshold
    return frame_step


def hybrid_vortex_frame_step(width: int, height: int,
                             grid_shape, axis_sign,
                             sim_steps: int = 3,
                             vdi_cfg: Optional[VDIConfig] = None,
                             tf: Optional[TransferFunction] = None,
                             radius: float = 0.02, stamp: int = 5,
                             colormap: str = "jet",
                             fov_y_deg: float = 50.0,
                             slicer_cfg=None,
                             background=(0.0, 0.0, 0.0, 0.0)):
    """Single-chip hybrid frame step (BASELINE.md Config 5): vortex-ring
    flow advanced in-situ, vorticity volume rendered as a VDI on the MXU
    slice march, passive tracers advected through the same flow and
    splatted as opaque spheres onto the SAME virtual-camera rays, then
    depth-correct merged (ops/hybrid.py) and warped to the display camera.

    Returns ``fn(u_flow, tracer_pos, eye) -> (image [4,H,W], u', pos')``
    (jittable). ``tracer_pos`` is in voxel coordinates (see
    vortex.seed_tracers).
    """
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.hybrid import composite_vdi_with_particles
    from scenery_insitu_tpu.sim import vortex

    tf = tf or for_dataset("hybrid")
    vdi_cfg = vdi_cfg or VDIConfig(max_supersegments=8, adaptive_iters=2)
    params = vortex.VortexParams.create()
    spec = slicer.make_spec(
        Camera.create((0.0, 0.6, 3.0), fov_y_deg=fov_y_deg),
        tuple(grid_shape), slicer_cfg, axis_sign=axis_sign)

    def frame_step(u_flow, tracer_pos, eye):
        flow = vortex.VortexFlow(u_flow, params)

        def advance(_, carry):
            fl, pos = carry
            pos = vortex.advect_tracers(fl.u, pos, params.dt)
            return vortex.step(fl), pos

        with _phase("sim_step"):
            flow, tracer_pos2 = jax.lax.fori_loop(0, sim_steps, advance,
                                                  (flow, tracer_pos))
        vol = Volume.centered(flow.field, extent=2.0)
        cam = Camera.create(eye, fov_y_deg=fov_y_deg, near=0.5, far=20.0)
        with _phase("march"):
            vdi, _, axcam = slicer.generate_vdi_mxu(vol, tf, cam, spec,
                                                    vdi_cfg)

        vel = vortex.tracer_velocities(flow.u, tracer_pos2)
        rgba = speed_colors(vel, colormap)
        world = vortex.tracers_to_world(tracer_pos2, vol.origin, vol.spacing)
        with _phase("march"):
            sp = splat_particles(world, rgba, radius, None, spec.ni,
                                 spec.nj, stamp, view=axcam.view,
                                 proj=axcam.proj)
        with _phase("merge"):
            inter = composite_vdi_with_particles(vdi, sp)
        img = slicer.warp_to_camera(inter, axcam, spec, cam, width, height,
                                    background)
        return img, flow.u, tracer_pos2

    return frame_step


def lj_particle_frame_step(width: int, height: int,
                           params: pt.LJParams, spec: pt.CellSpec,
                           sim_steps: int = 5, radius: float = 0.35,
                           stamp: int = 9, colormap: str = "jet",
                           fov_y_deg: float = 50.0):
    """Single-chip in-situ particle frame step: Lennard-Jones MD advance →
    speed-colored sphere splatting (the particle analog of the VDI flagship;
    ≅ the reference's InVisRenderer loop, InVisRenderer.kt:119-209).
    Returns ``fn(pos, vel, box, eye) -> (image, depth, pos, vel)``.

    ``params``/``spec`` must come from ``particles.lj_init`` (or satisfy the
    same invariant: box/ncell >= cutoff*sigma, or in-range pairs get dropped
    from the 27-cell neighborhood)."""

    def frame_step(pos, vel, box, eye):
        state = pt.ParticleState(pos, vel, box)
        with _phase("sim_step"):
            state = pt.lj_multi_step(state, params, spec, sim_steps)
        cam = Camera.create(eye, target=(0.0, 0.0, 0.0),
                            fov_y_deg=fov_y_deg, near=0.5, far=100.0)
        # center the box on the origin for viewing
        centered = state.pos - state.box / 2.0
        rgba = speed_colors(state.vel, colormap)
        out = splat_particles(centered, rgba, radius, cam, width, height,
                              stamp)
        return out.image, out.depth, state.pos, state.vel

    return frame_step
