from scenery_insitu_tpu.sim.grayscott import GrayScott  # noqa: F401
