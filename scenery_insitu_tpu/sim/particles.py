"""Particle simulations: Lennard-Jones MD (BASELINE.md Config 4) and the
simple-harmonic-oscillator toy sim the reference uses as the fake transport
workload (its shm producer runs an SHO particle grid —
src/test/cpp/shm_mpiproducer.cpp:85-122).

LJ uses a fixed-capacity cell list rebuilt every step: particles are sorted
by cell id and each particle gathers candidates from its 27 neighbor cells —
static shapes throughout (capacity overflow drops the farthest extras, the
standard JAX-MD-style trade), so the whole step jits to dense gathers +
vectorized arithmetic. Velocity-Verlet integration, periodic box.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class ParticleState(NamedTuple):
    pos: jnp.ndarray     # f32[N, 3] in [0, box)
    vel: jnp.ndarray     # f32[N, 3]
    box: jnp.ndarray     # f32[] periodic box side
    # ≅ the reference's per-particle "props" buffer (velocity/force planes,
    # InVisRenderer.kt:149-163): consumers read .vel (or forces) for coloring


# ----------------------------------------------------------------- SHO sim

class SHOParams(NamedTuple):
    omega2: jnp.ndarray
    dt: jnp.ndarray


def sho_init(n: int, box: float = 1.0, seed: int = 0,
             omega2: float = 4.0, dt: float = 0.005
             ) -> Tuple[ParticleState, SHOParams]:
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    pos = jax.random.uniform(k1, (n, 3), jnp.float32, 0.0, box)
    vel = jax.random.normal(k2, (n, 3), jnp.float32) * 0.1 * box
    return (ParticleState(pos, vel, jnp.float32(box)),
            SHOParams(jnp.float32(omega2), jnp.float32(dt)))


def sho_step(state: ParticleState, p: SHOParams) -> ParticleState:
    """Each particle oscillates about the box center (matches the
    reference workload's independent-oscillator update)."""
    center = state.box / 2.0
    acc = -p.omega2 * (state.pos - center)
    vel = state.vel + p.dt * acc
    pos = state.pos + p.dt * vel
    return state._replace(pos=pos, vel=vel)


# ------------------------------------------------------------------- LJ MD

class LJParams(NamedTuple):
    epsilon: jnp.ndarray
    sigma: jnp.ndarray
    cutoff: jnp.ndarray     # in units of sigma
    dt: jnp.ndarray

    @classmethod
    def create(cls, epsilon=1.0, sigma=1.0, cutoff=2.5, dt=0.002):
        a = lambda x: jnp.asarray(x, jnp.float32)
        return cls(a(epsilon), a(sigma), a(cutoff), a(dt))


class CellSpec(NamedTuple):
    """Static cell-list geometry (python ints so shapes stay static)."""
    ncell: int            # cells per axis
    capacity: int         # max particles per cell


def lj_init(n: int, density: float = 0.5, params: Optional[LJParams] = None,
            seed: int = 0, temperature: float = 1.0
            ) -> Tuple[ParticleState, LJParams, CellSpec]:
    """Particles on a jittered cubic lattice (avoids overlapping starts)."""
    params = params or LJParams.create()
    box = float((n / density) ** (1.0 / 3.0))
    side = int(jnp.ceil(n ** (1.0 / 3.0)))
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    idx = jnp.arange(side ** 3)[:n]
    lattice = jnp.stack([idx % side, (idx // side) % side,
                         idx // (side * side)], axis=-1).astype(jnp.float32)
    spacing = box / side
    pos = (lattice + 0.5) * spacing
    pos = pos + jax.random.uniform(k1, (n, 3), jnp.float32,
                                   -0.1 * spacing, 0.1 * spacing)
    vel = jax.random.normal(k2, (n, 3), jnp.float32) * jnp.sqrt(temperature)
    vel = vel - vel.mean(axis=0, keepdims=True)
    rc = float(params.cutoff * params.sigma)
    ncell = max(int(box / rc), 3)
    mean_occ = n / ncell ** 3
    capacity = max(int(mean_occ * 3) + 4, 8)
    return (ParticleState(pos, vel, jnp.float32(box)), params,
            CellSpec(ncell, capacity))


def _build_cells(pos: jnp.ndarray, box: jnp.ndarray, spec: CellSpec
                 ) -> jnp.ndarray:
    """-> i32[ncell^3, capacity] particle indices per cell (N = sentinel)."""
    n = pos.shape[0]
    nc = spec.ncell
    cell = jnp.clip((pos / (box / nc)).astype(jnp.int32), 0, nc - 1)
    cid = (cell[:, 2] * nc + cell[:, 1]) * nc + cell[:, 0]
    order = jnp.argsort(cid)
    cid_sorted = cid[order]
    # rank of each particle within its cell
    start = jnp.searchsorted(cid_sorted, jnp.arange(nc ** 3), side="left")
    rank = jnp.arange(n) - start[cid_sorted]
    table = jnp.full((nc ** 3, spec.capacity), n, jnp.int32)
    # rank >= capacity falls out of bounds and is dropped (overflow policy)
    table = table.at[cid_sorted, rank].set(order, mode="drop")
    return table


def lj_forces(pos: jnp.ndarray, box: jnp.ndarray, params: LJParams,
              spec: CellSpec) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (forces f32[N, 3], potential f32[]) from the 27-cell neighborhood."""
    n = pos.shape[0]
    nc = spec.ncell
    table = _build_cells(pos, box, spec)                 # [nc^3, cap]
    cell = jnp.clip((pos / (box / nc)).astype(jnp.int32), 0, nc - 1)

    # 27 neighbor cell ids per particle
    offs = jnp.stack(jnp.meshgrid(*([jnp.arange(-1, 2)] * 3),
                                  indexing="ij"), axis=-1).reshape(-1, 3)
    ncell_ids = jnp.mod(cell[:, None, :] + offs[None], nc)   # [N, 27, 3]
    nid = (ncell_ids[..., 2] * nc + ncell_ids[..., 1]) * nc + ncell_ids[..., 0]
    cand = table[nid].reshape(n, -1)                     # [N, 27*cap]

    pos_pad = jnp.concatenate([pos, jnp.zeros((1, 3), pos.dtype)])
    rj = pos_pad[cand]                                   # [N, M, 3]
    dr = pos[:, None, :] - rj
    dr = dr - box * jnp.round(dr / box)                  # minimum image
    r2 = jnp.sum(dr * dr, axis=-1)
    valid = (cand < n) & (cand != jnp.arange(n)[:, None]) \
        & (r2 < (params.cutoff * params.sigma) ** 2)
    r2 = jnp.where(valid, r2, 1e10)
    inv2 = (params.sigma ** 2) / r2
    inv6 = inv2 ** 3
    # F = 24 eps (2 s^12/r^13 - s^6/r^7) rhat = 24 eps (2 inv6^2 - inv6)/r2 * dr
    fmag = 24.0 * params.epsilon * (2.0 * inv6 * inv6 - inv6) / r2
    forces = jnp.sum(jnp.where(valid[..., None], fmag[..., None] * dr, 0.0),
                     axis=1)
    pot = 2.0 * params.epsilon * jnp.sum(
        jnp.where(valid, inv6 * inv6 - inv6, 0.0))       # 4eps/2 double count
    return forces, pot


def lj_step(state: ParticleState, params: LJParams, spec: CellSpec,
            forces: Optional[jnp.ndarray] = None
            ) -> Tuple[ParticleState, jnp.ndarray]:
    """One velocity-Verlet step; returns (state, new forces) so callers can
    reuse forces across steps."""
    if forces is None:
        forces, _ = lj_forces(state.pos, state.box, params, spec)
    vel_half = state.vel + 0.5 * params.dt * forces
    pos = jnp.mod(state.pos + params.dt * vel_half, state.box)
    new_forces, _ = lj_forces(pos, state.box, params, spec)
    vel = vel_half + 0.5 * params.dt * new_forces
    return state._replace(pos=pos, vel=vel), new_forces


@partial(jax.jit, static_argnums=(2, 3))
def lj_multi_step(state: ParticleState, params: LJParams, spec: CellSpec,
                  n: int) -> ParticleState:
    def body(_, carry):
        st, f = carry
        return lj_step(st, params, spec, f)
    f0, _ = lj_forces(state.pos, state.box, params, spec)
    st, _ = jax.lax.fori_loop(0, n, body, (state, f0))
    return st


def kinetic_energy(state: ParticleState) -> jnp.ndarray:
    return 0.5 * jnp.sum(state.vel ** 2)


def speeds(state: ParticleState) -> jnp.ndarray:
    return jnp.linalg.norm(state.vel, axis=-1)
