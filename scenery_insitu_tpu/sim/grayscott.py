"""Gray-Scott 3D reaction-diffusion — the reference's headline demo workload
(README.md:4-8 gray_scott.gif ran on OpenFPM across 8 nodes; here it is a
built-in JAX simulation so the framework runs standalone, which the
reference explicitly could not: README.md:16 "can not be used standalone").

The update is pure elementwise + 6-point Laplacian stencil (periodic BC via
jnp.roll), so under jit with a z-sharded state XLA lowers the rolls to
ppermute halo exchanges over ICI automatically — the same decomposition the
render pipeline uses.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import SimConfig


class GrayScottParams(NamedTuple):
    f: jnp.ndarray      # feed rate
    k: jnp.ndarray      # kill rate
    du: jnp.ndarray     # diffusion of u
    dv: jnp.ndarray     # diffusion of v
    dt: jnp.ndarray

    @classmethod
    def create(cls, f=None, k=None, du=None, dv=None, dt=None):
        # defaults come from SimConfig — single source of truth
        d = SimConfig()
        a = lambda x, dflt: jnp.asarray(dflt if x is None else x, jnp.float32)
        return cls(a(f, d.gs_f), a(k, d.gs_k), a(du, d.gs_du),
                   a(dv, d.gs_dv), a(dt, d.dt))


class GrayScott(NamedTuple):
    u: jnp.ndarray      # f32[D, H, W]
    v: jnp.ndarray      # f32[D, H, W]
    params: GrayScottParams

    @classmethod
    def init(cls, grid: Tuple[int, int, int], params: GrayScottParams = None,
             seed: int = 0, n_seeds: int = 4) -> "GrayScott":
        """Uniform u=1, v=0 with one central seed cube (quarter-width — small
        seeds diffuse away in 3D) plus ``n_seeds`` random satellite cubes."""
        d, h, w = grid
        u = jnp.ones(grid, jnp.float32)
        v = jnp.zeros(grid, jnp.float32)
        zz, yy, xx = jnp.meshgrid(jnp.arange(d), jnp.arange(h),
                                  jnp.arange(w), indexing="ij")

        def stamp(u, v, c, r):
            mask = ((jnp.abs(zz - c[0]) < r) & (jnp.abs(yy - c[1]) < r)
                    & (jnp.abs(xx - c[2]) < r))
            return jnp.where(mask, 0.5, u), jnp.where(mask, 0.25, v)

        u, v = stamp(u, v, (d // 2, h // 2, w // 2), max(min(d, h, w) // 4, 2))
        key = jax.random.PRNGKey(seed)
        rs = max(min(d, h, w) // 8, 2)
        for k in jax.random.split(key, n_seeds):
            c = jax.random.randint(k, (3,), rs,
                                   jnp.array([d - rs, h - rs, w - rs]))
            u, v = stamp(u, v, c, rs)
        return cls(u, v, params or GrayScottParams.create())

    @classmethod
    def from_config(cls, cfg: SimConfig, seed: int = 0) -> "GrayScott":
        return cls.init(tuple(cfg.grid),
                        GrayScottParams.create(cfg.gs_f, cfg.gs_k,
                                               cfg.gs_du, cfg.gs_dv, cfg.dt),
                        seed=seed)

    @property
    def field(self) -> jnp.ndarray:
        """The scalar field rendered in-situ (v concentration, ≈[0, 1])."""
        return self.v


def _laplacian(x: jnp.ndarray) -> jnp.ndarray:
    return (jnp.roll(x, 1, 0) + jnp.roll(x, -1, 0)
            + jnp.roll(x, 1, 1) + jnp.roll(x, -1, 1)
            + jnp.roll(x, 1, 2) + jnp.roll(x, -1, 2) - 6.0 * x)


def step(state: GrayScott) -> GrayScott:
    u, v, p = state.u, state.v, state.params
    uvv = u * v * v
    du = p.du * _laplacian(u) - uvv + p.f * (1.0 - u)
    dv = p.dv * _laplacian(v) + uvv - (p.f + p.k) * v
    return GrayScott(u + p.dt * du, v + p.dt * dv, p)


@partial(jax.jit, static_argnums=1)
def multi_step(state: GrayScott, n: int) -> GrayScott:
    return jax.lax.fori_loop(0, n, lambda _, s: step(s), state)


def multi_step_fast(state: GrayScott, n: int) -> GrayScott:
    """Single-device fast path: the fused Pallas stencil kernel on TPU
    (sim/pallas_stencil.py, ~10x the roll formulation), falling back to
    `multi_step` on other backends or VMEM-oversized grids. NOT for sharded
    state — the Pallas kernel's periodic wrap is per-buffer, so use
    `multi_step` (whose rolls XLA lowers to ICI halo exchanges) there."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    if jax.default_backend() != "tpu":
        # ledger only (warn=False): this runs per frame and the off-TPU
        # downgrade is expected platform behavior — but a run that was
        # CONFIGURED fused and silently ran the roll path must still end
        # with that fact on the record (deduped, counted)
        obs.degrade("sim.fused_stencil", "pallas", "xla_roll",
                    f"backend is {jax.default_backend()!r}, not tpu",
                    warn=False)
        return multi_step(state, n)
    if not ps.fused_supported(state.u.shape):
        obs.degrade("sim.fused_stencil", "pallas", "xla_roll",
                    f"no fused-stencil schedule fits grid "
                    f"{tuple(state.u.shape)} in the VMEM budget",
                    warn=False)
        return multi_step(state, n)
    p = state.params
    pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
    u, v = ps.multi_step_pallas(state.u, state.v, pvec, n)
    return GrayScott(u, v, p)


def multi_step_fast_ranges(state: GrayScott, n: int, bricks=None,
                           fused: bool = True):
    """`multi_step_fast` that ALSO returns per-brick min/max of the
    rendered field (ops/occupancy.FieldRanges) — the sim-fused update of
    the frame's occupancy pyramid. The fused Pallas path emits the
    ranges as a kernel epilogue (near-free: the slab is already in
    VMEM); every degraded path (off-TPU, VMEM-oversized grid, Mosaic
    rejection of the epilogue variant, or ``fused=False`` pinning the
    XLA roll formulation) falls back to ONE lax reduction over the final
    field in data layout (`occupancy.field_ranges` — still cheaper than
    the legacy permute+reduce occupancy pass, and recorded on the
    fallback ledger unless the roll path was explicitly configured).

    ``bricks = (nzb, nyb)`` is the brick GRID (defaults to
    `occupancy.default_bricks`). Returns ``(state', FieldRanges)``."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.ops import occupancy as occ
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    nzb, nyb = bricks or occ.default_bricks(state.v.shape)
    if (fused and jax.default_backend() == "tpu"
            and ps.fused_supported(state.u.shape)
            and ps.ranges_supported(state.u.shape)):
        p = state.params
        pvec = jnp.stack([p.f, p.k, p.du, p.dv, p.dt])
        u, v, lo, hi = ps.multi_step_pallas_ranges(state.u, state.v,
                                                   pvec, n, nzb, nyb)
        return GrayScott(u, v, p), occ.FieldRanges(lo, hi)
    if fused:
        # configured fused but the epilogue cannot ride the kernel: the
        # advance itself still takes its own best path (multi_step_fast
        # ledgers its own degradations); only the ranges fall back here
        obs.degrade("occupancy.sim_ranges", "fused_epilogue",
                    "lax_reduce",
                    f"backend={jax.default_backend()!r}, grid="
                    f"{tuple(state.u.shape)}: no fused ranges schedule",
                    warn=False)
        st = multi_step_fast(state, n)
    else:
        st = multi_step(state, n)
    return st, occ.field_ranges(st.field, nzb, nyb)
