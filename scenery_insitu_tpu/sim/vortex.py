"""Vortex-ring Navier-Stokes simulation (BASELINE.md Config 3; ≅ the
reference's vortex-in-cell OpenFPM demo, README.md:4-8 vortex_in_cell.gif,
whose vorticity-magnitude volume is rendered in-situ).

A stable-fluids incompressible solver on a periodic box, built from
TPU-friendly primitives only:

- semi-Lagrangian advection (trilinear back-trace via the same gather
  sampler the renderer uses),
- spectral diffusion + pressure projection in one rFFT round-trip
  (jnp.fft; exact div-free projection, unconditionally stable).

State is velocity ``u f32[3, D, H, W]``; the rendered field is |curl u|
(vorticity magnitude), normalized to ≈[0, 1].
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.ops.sampling import sample_trilinear


class VortexParams(NamedTuple):
    viscosity: jnp.ndarray   # kinematic viscosity
    dt: jnp.ndarray

    @classmethod
    def create(cls, viscosity=1e-3, dt=0.1):
        a = lambda x: jnp.asarray(x, jnp.float32)
        return cls(a(viscosity), a(dt))


class VortexFlow(NamedTuple):
    u: jnp.ndarray           # f32[3, D, H, W] velocity (x, y, z components)
    params: VortexParams

    @classmethod
    def init_ring(cls, grid: Tuple[int, int, int],
                  params: VortexParams = None, rings: int = 2,
                  radius: float = 0.22, strength: float = 6.0) -> "VortexFlow":
        """One or two coaxial vortex rings travelling along +z (two rings
        leapfrog — the classic demo)."""
        d, h, w = grid
        z, y, x = jnp.meshgrid(
            (jnp.arange(d) + 0.5) / d - 0.5,
            (jnp.arange(h) + 0.5) / h - 0.5,
            (jnp.arange(w) + 0.5) / w - 0.5, indexing="ij")
        u = jnp.zeros((3, d, h, w), jnp.float32)
        offsets = [-0.12, 0.12][:rings] if rings > 1 else [0.0]
        for zo in offsets:
            # solid-core ring vorticity -> induced velocity via stream fn
            # approximation: add a swirling velocity field around the ring
            # core circle (x²+y² = radius², z = zo)
            rho = jnp.sqrt(x * x + y * y) + 1e-6
            # distance from the ring core
            dr = jnp.sqrt((rho - radius) ** 2 + (z - zo) ** 2)
            core = 0.05
            swirl = strength * jnp.exp(-(dr / core) ** 2 / 2)
            # toroidal vorticity direction: (-y/rho, x/rho, 0); velocity
            # circulates in the (rho, z) plane around the core:
            #   u_rho ∝ -(z - zo), u_z ∝ (rho - radius)
            u_rho = -swirl * (z - zo) / (dr + 1e-6) * core
            u_z = swirl * (rho - radius) / (dr + 1e-6) * core
            u = u.at[0].add(u_rho * x / rho)
            u = u.at[1].add(u_rho * y / rho)
            u = u.at[2].add(u_z)
        # velocity is kept in voxel units / time everywhere (advection
        # back-traces in voxel coords); the ring was built in domain units
        scale = jnp.array([w, h, d], jnp.float32).reshape(3, 1, 1, 1)
        flow = cls(u * scale, params or VortexParams.create())
        return flow._replace(u=project_divfree(flow.u, flow.params, 0.0))

    @property
    def field(self) -> jnp.ndarray:
        """Normalized vorticity magnitude f32[D, H, W] for rendering."""
        w = vorticity(self.u)
        mag = jnp.sqrt(jnp.sum(w * w, axis=0))
        return mag / (jnp.max(mag) + 1e-6)


def _grad_axes(shape):
    """Periodic spectral wavenumbers for (D, H, W) with Nyquist bins zeroed:
    the Nyquist derivative is sign-ambiguous and a nonzero choice breaks the
    Hermitian symmetry of the projected spectrum (irfft then silently drops
    the asymmetric part, leaving divergence behind)."""
    d, h, w = shape

    def axis_freqs(n, rfft=False):
        k = (jnp.fft.rfftfreq(n) if rfft else jnp.fft.fftfreq(n)) * 2 * jnp.pi
        if n % 2 == 0:
            k = k.at[-1 if rfft else n // 2].set(0.0)
        return k

    return jnp.meshgrid(axis_freqs(d), axis_freqs(h), axis_freqs(w, True),
                        indexing="ij")


def vorticity(u: jnp.ndarray) -> jnp.ndarray:
    """curl(u) via central differences on the periodic grid (grid units)."""
    def dd(f, axis):
        return 0.5 * (jnp.roll(f, -1, axis) - jnp.roll(f, 1, axis))
    ux, uy, uz = u[0], u[1], u[2]
    # axes of f[D, H, W]: 0=z, 1=y, 2=x
    wx = dd(uz, 1) - dd(uy, 0)
    wy = dd(ux, 0) - dd(uz, 2)
    wz = dd(uy, 2) - dd(ux, 1)
    return jnp.stack([wx, wy, wz])


def advect_semilagrangian(u: jnp.ndarray, dt: jnp.ndarray) -> jnp.ndarray:
    """Back-trace each grid point through the velocity field and resample
    (periodic wrap)."""
    _, d, h, w = u.shape
    z, y, x = jnp.meshgrid(jnp.arange(d, dtype=jnp.float32) + 0.5,
                           jnp.arange(h, dtype=jnp.float32) + 0.5,
                           jnp.arange(w, dtype=jnp.float32) + 0.5,
                           indexing="ij")
    # velocity components are in grid-units / time
    bx = jnp.mod(x - dt * u[0], w)
    by = jnp.mod(y - dt * u[1], h)
    bz = jnp.mod(z - dt * u[2], d)
    pos = jnp.stack([bx, by, bz], axis=-1)

    def samp(f):
        # pad one wrap layer on BOTH faces (and shift coords by +1) so the
        # clamped trilinear sampler interpolates periodically across the low
        # boundary too — positions in [0, 0.5) must blend f[0] with f[n-1]
        fp = jnp.pad(f, ((1, 1), (1, 1), (1, 1)), mode="wrap")
        return sample_trilinear(fp, pos + 1.0)

    return jnp.stack([samp(u[0]), samp(u[1]), samp(u[2])])


def project_divfree(u: jnp.ndarray, params: VortexParams,
                    dt_override=None) -> jnp.ndarray:
    """Spectral viscous decay + exact Leray projection onto div-free fields."""
    dt = params.dt if dt_override is None else jnp.asarray(dt_override, jnp.float32)
    _, d, h, w = u.shape
    kz, ky, kx = _grad_axes((d, h, w))
    k2 = kx * kx + ky * ky + kz * kz
    uh = jnp.stack([jnp.fft.rfftn(u[i]) for i in range(3)])
    decay = jnp.exp(-params.viscosity * k2 * dt)
    uh = uh * decay
    # remove the component along k: uh -= k (k·uh)/k²
    kdotu = kx * uh[0] + ky * uh[1] + kz * uh[2]
    k2s = jnp.where(k2 == 0, 1.0, k2)
    uh = uh - jnp.stack([kx, ky, kz]) * (kdotu / k2s)
    return jnp.stack([jnp.fft.irfftn(uh[i], s=(d, h, w)) for i in range(3)]
                     ).astype(jnp.float32)


def seed_tracers(grid: Tuple[int, int, int], n: int,
                 seed: int = 0) -> jnp.ndarray:
    """f32[N, 3] tracer positions in voxel coordinates (x, y, z), seeded
    uniformly in the central half of the box (where the rings live)."""
    d, h, w = grid
    key = jax.random.PRNGKey(seed)
    u01 = jax.random.uniform(key, (n, 3))
    lo = jnp.array([w * 0.25, h * 0.25, d * 0.25], jnp.float32)
    ext = jnp.array([w * 0.5, h * 0.5, d * 0.5], jnp.float32)
    return lo + u01 * ext


def tracer_velocities(u: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """Sample the flow velocity at tracer positions -> f32[N, 3] (vx,vy,vz
    in voxel units/time). Periodic wrap via the same pad trick as
    advect_semilagrangian; sample_trilinear expects [D, H, W] + (x,y,z)."""
    def samp(f):
        fp = jnp.pad(f, ((1, 1), (1, 1), (1, 1)), mode="wrap")
        return sample_trilinear(fp, pos + 1.0)

    return jnp.stack([samp(u[0]), samp(u[1]), samp(u[2])], axis=-1)


def advect_tracers(u: jnp.ndarray, pos: jnp.ndarray,
                   dt: jnp.ndarray) -> jnp.ndarray:
    """Advect passive tracers through the flow (BASELINE.md Config 5's
    500k-tracer hybrid). pos f32[N, 3] voxel coords (x, y, z); periodic
    wrap. One forward-Euler step per call — the flow field is smooth and
    the dt matches the solver's."""
    _, d, h, w = u.shape
    vel = tracer_velocities(u, pos)
    box = jnp.array([w, h, d], jnp.float32)
    return jnp.mod(pos + dt * vel, box)


def tracers_to_world(pos: jnp.ndarray, origin: jnp.ndarray,
                     spacing: jnp.ndarray) -> jnp.ndarray:
    """Voxel-coordinate tracers -> world positions (x, y, z)."""
    return origin + pos * spacing


def step(flow: VortexFlow) -> VortexFlow:
    u = advect_semilagrangian(flow.u, flow.params.dt)
    u = project_divfree(u, flow.params)
    return flow._replace(u=u)


@partial(jax.jit, static_argnums=1)
def multi_step(flow: VortexFlow, n: int) -> VortexFlow:
    return jax.lax.fori_loop(0, n, lambda _, f: step(f), flow)
