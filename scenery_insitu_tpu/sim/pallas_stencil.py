"""Pallas TPU kernel for the Gray-Scott reaction-diffusion step.

The XLA formulation (sim/grayscott.py) builds the 6-point Laplacian from
``jnp.roll`` — twelve materialized full-volume copies per step, ~3 ms at
256³ on a v5e (≈8× above memory-bound). This kernel fuses ``T`` whole
steps into a single pass: each grid step holds a ``[Tz + 2T, H, W]`` slab
of u and v in VMEM (the slab plus a T-slice halo on each z side, taken
from neighbor views of the same HBM arrays with periodic wrap in the
BlockSpec index_map), advances it T times entirely in VMEM — in-plane
neighbors by register shifts, z-halo validity shrinking by one slice per
step so the central Tz slices are exact — and writes the updated slab
once. Per T steps the volume is read ``(Tz+2T)/Tz`` ≈ 1.25× and written
1×, so HBM traffic per step drops by ~T× over the single-step kernel at
the cost of ``2T/Tz`` redundant stencil work.

Used by the single-device fast path only: the *sharded* simulation keeps
the roll formulation, where XLA lowers the rolls across a z-sharded mesh
to ICI halo collectives (see sim/grayscott.py docstring) — a Pallas kernel
with per-shard periodic wrap would silently corrupt shard boundaries.

On CPU the kernel runs in interpret mode (used by the parity test); the
production CPU path stays on the XLA formulation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# nominal bytes of live blocks per grid step; Mosaic double-buffers the
# pipelined inputs/outputs, so this must stay well under the part's VMEM.
# The figure is a HEURISTIC screen only — `fused_supported` /
# `multi_step_pallas` verify each (shape, T) choice with a one-time
# Mosaic compile probe and degrade to smaller T / the XLA roll path, so
# the budget's job is merely to skip probing hopeless candidates. The
# original 24 MB default silently pinned the 512^3 flagship to T=1
# (full 2 GB/step HBM traffic, ~20 GB of the measured 29 GB frame);
# 96 MB admits T=2/tz=4 (40 MB nominal) and lets the probe — not the
# heuristic — decide what this part's 128 MB VMEM really accepts.
_VMEM_BUDGET = int(os.environ.get("SITPU_STENCIL_VMEM_MB", "96")) \
    * 1024 * 1024

# (shape, t_steps) -> did Mosaic accept the fused kernel?
_PROBE_CACHE: dict = {}


def _compile_ok(shape, t_steps: int, tz: int = 0) -> bool:
    """One-time probe: does the fused kernel at this (shape, T, tz)
    actually compile on the current TPU? A VMEM budget miss surfaces as a
    Mosaic resource-exhausted error at compile time — catch it HERE,
    where a fallback exists, not inside a traced frame step where it
    cannot be caught. Cached per process (and cheap on repeats via the
    persistent JAX compile cache)."""
    key = (tuple(shape), int(t_steps), int(tz))
    ok = _PROBE_CACHE.get(key)
    if ok is None:
        try:
            s = jax.ShapeDtypeStruct(tuple(shape), jnp.float32)
            p = jax.ShapeDtypeStruct((5,), jnp.float32)
            step_pallas.lower(s, s, p, t_steps=t_steps, tz=tz).compile()
            ok = True
        except Exception:
            ok = False
        _PROBE_CACHE[key] = ok
    return ok


def fused_supported(shape, t_steps: int = 1) -> bool:
    """Can the fused kernel run this grid on the current backend? True iff
    a slab fits the nominal budget AND (on TPU) Mosaic accepts the
    kernel. The gate `sim.grayscott.multi_step_fast` consults before
    choosing the Pallas path."""
    cands = tz_candidates(shape, t_steps)
    if not cands:
        return False
    if jax.default_backend() != "tpu":
        return True          # interpret mode has no VMEM to exhaust
    return any(_compile_ok(shape, t_steps, c) for c in cands[:2])


def _roll(x: jnp.ndarray, shift: int, axis: int) -> jnp.ndarray:
    """Periodic shift via the Mosaic rotate primitive (a slice+concat
    formulation forces unaligned sublane/lane relayouts and is ~20x
    slower)."""
    return pltpu.roll(x, shift % x.shape[axis], axis)


def _kernel(t_steps, p_ref, u_ref, v_ref, uzm_ref, uzp_ref, vzm_ref,
            vzp_ref, uo_ref, vo_ref):
    f, k, du, dv, dt = (p_ref[i] for i in range(5))
    t = t_steps
    u = jnp.concatenate([uzm_ref[...], u_ref[...], uzp_ref[...]], axis=0)
    v = jnp.concatenate([vzm_ref[...], v_ref[...], vzp_ref[...]], axis=0)

    def lap(x):
        # z neighbors by shift with edge replication: the outermost slice
        # of the halo goes stale anyway (validity shrinks 1 slice per
        # step from each end; after T steps the central Tz are exact)
        zm = jnp.concatenate([x[:1], x[:-1]], axis=0)
        zp = jnp.concatenate([x[1:], x[-1:]], axis=0)
        return (zm + zp
                + _roll(x, 1, 1) + _roll(x, -1, 1)
                + _roll(x, 1, 2) + _roll(x, -1, 2) - 6.0 * x)

    for _ in range(t):
        uvv = u * v * v
        u, v = (u + dt * (du * lap(u) - uvv + f * (1.0 - u)),
                v + dt * (dv * lap(v) + uvv - (f + k) * v))

    uo_ref[...] = u[t:u.shape[0] - t]
    vo_ref[...] = v[t:v.shape[0] - t]


def tz_candidates(shape, t_steps: int = 1) -> tuple:
    """z-slab sizes for a T-step fused call fitting the VMEM budget and
    the divisibility constraints, largest first: tz | D so the grid tiles
    exactly, and T | tz so the T-slice halos are expressible as whole
    (T, H, W) blocks. The budget is a screen; the Mosaic compile probe
    (`_compile_ok`) is the authority, so `multi_step_pallas` walks this
    list until one compiles instead of betting everything on the
    nominal-largest choice."""
    d, h, w = shape
    plane = h * w * 4
    out = []
    for tz in (32, 16, 8, 4, 2, 1):
        if d % tz or tz % t_steps:
            continue
        # live VMEM: ~4 arrays (u, v and temporaries) of the haloed slab
        # plus the two output slabs
        if (4 * (tz + 2 * t_steps) + 2 * tz) * plane <= _VMEM_BUDGET:
            out.append(tz)
    return tuple(out)


def pick_tz(shape, t_steps: int = 1) -> int:
    """Largest nominally-fitting z-slab size (0 = none fits)."""
    cands = tz_candidates(shape, t_steps)
    return cands[0] if cands else 0


@functools.partial(jax.jit, static_argnames=("t_steps", "interpret", "tz"))
def step_pallas(u: jnp.ndarray, v: jnp.ndarray, params_vec: jnp.ndarray,
                t_steps: int = 1, interpret: bool = False, tz: int = 0):
    """Advance ``t_steps`` Gray-Scott steps in one fused kernel pass.
    ``params_vec = [f, k, du, dv, dt]`` (f32[5]). Requires
    ``pick_tz(u.shape, t_steps) > 0``. ``tz=0`` auto-picks the largest
    nominally-fitting slab; an explicit tz must come from
    `tz_candidates`."""
    d, h, w = u.shape
    t = t_steps
    tz = tz or pick_tz(u.shape, t)
    if tz == 0:
        raise ValueError(
            f"grid {u.shape} does not fit the VMEM budget at T={t}")
    nb = d // tz
    nb_t = d // t                 # array length in halo-block units

    slab = pl.BlockSpec((tz, h, w), lambda i: (i, 0, 0))
    # T-slice halo views of the same arrays; index_map is in units of the
    # (T, H, W) block shape, so periodic wrap is exact (T | tz makes the
    # offsets whole blocks)
    r = tz // t
    zm = pl.BlockSpec((t, h, w), lambda i: ((i * r - 1) % nb_t, 0, 0))
    zp = pl.BlockSpec((t, h, w), lambda i: ((i + 1) * r % nb_t, 0, 0))

    return pl.pallas_call(
        functools.partial(_kernel, t),
        grid=(nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  slab, slab, zm, zp, zm, zp],
        out_specs=[slab, slab],
        out_shape=[jax.ShapeDtypeStruct((d, h, w), jnp.float32)] * 2,
        interpret=interpret,
    )(params_vec, u, v, u, u, v, v)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def multi_step_pallas(u, v, params_vec, n: int, interpret: bool = False):
    """n Gray-Scott steps, fused ``_FUSE_T`` at a time; the remainder runs
    at progressively smaller fusion factors (greedy decomposition, so e.g.
    n=5 runs one T=4 pass + one T=1 pass instead of silently degrading the
    whole loop to T=1)."""
    s = (u, v)
    remaining = n
    on_tpu = jax.default_backend() == "tpu" and not interpret
    for t in range(min(_FUSE_T, n), 0, -1):
        reps = remaining // t
        cands = tz_candidates(u.shape, t)
        if reps == 0 or not cands:
            continue
        if on_tpu:
            # walk the two largest nominal fits — the budget is a screen
            # and Mosaic the authority, but each probe is a real compile,
            # so the walk is capped to keep warmup bounded
            tz = next((c for c in cands[:2]
                       if _compile_ok(u.shape, t, c)), 0)
            if tz == 0:
                continue     # Mosaic rejected this T: degrade, don't die
        else:
            tz = cands[0]
        s = jax.lax.fori_loop(
            0, reps, lambda _, s, t=t, tz=tz: step_pallas(
                s[0], s[1], params_vec, t, interpret=interpret, tz=tz),
            s)
        remaining -= reps * t
        if remaining == 0:
            break
    if remaining:   # pick_tz(shape, 1) == 0: caller should have gated
        raise ValueError(f"grid {u.shape} does not fit the VMEM budget")
    return s


_FUSE_T = 4
