"""Pallas TPU kernel for the Gray-Scott reaction-diffusion step.

The XLA formulation (sim/grayscott.py) builds the 6-point Laplacian from
``jnp.roll`` — twelve materialized full-volume copies per step, ~4.9 ms at
256³ on a v5e (≈15× above memory-bound). This kernel fuses one whole step
into a single pass: each grid step holds a ``[Tz, H, W]`` slab of u and v
in VMEM, takes its two z-halo slices from one-slice neighbor views of the
same HBM arrays (periodic wrap in the BlockSpec index_map), computes the
in-plane neighbors by register shifts inside the kernel, and writes the
updated slab once. Per step the volume is read ~1.25× and written 1×.

Used by the single-device fast path only: the *sharded* simulation keeps
the roll formulation, where XLA lowers the rolls across a z-sharded mesh
to ICI halo collectives (see sim/grayscott.py docstring) — a Pallas kernel
with per-shard periodic wrap would silently corrupt shard boundaries.

On CPU the kernel runs in interpret mode (used by the parity test); the
production CPU path stays on the XLA formulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# nominal bytes of live blocks per grid step; Mosaic double-buffers the
# pipelined inputs/outputs, so this must stay under half the ~16 MB VMEM
_VMEM_BUDGET = 7 * 1024 * 1024


def _roll(x: jnp.ndarray, shift: int, axis: int) -> jnp.ndarray:
    """Periodic shift via the Mosaic rotate primitive (a slice+concat
    formulation forces unaligned sublane/lane relayouts and is ~20x
    slower)."""
    return pltpu.roll(x, shift % x.shape[axis], axis)


def _kernel(p_ref, u_ref, v_ref, uzm_ref, uzp_ref, vzm_ref, vzp_ref,
            uo_ref, vo_ref):
    f, k, du, dv, dt = (p_ref[i] for i in range(5))
    u = u_ref[...]                                   # [Tz, H, W]
    v = v_ref[...]

    def lap(x, zm_ref, zp_ref):
        zm = jnp.concatenate([zm_ref[...], x[:-1]], axis=0)
        zp = jnp.concatenate([x[1:], zp_ref[...]], axis=0)
        return (zm + zp
                + _roll(x, 1, 1) + _roll(x, -1, 1)
                + _roll(x, 1, 2) + _roll(x, -1, 2) - 6.0 * x)

    uvv = u * v * v
    uo_ref[...] = u + dt * (du * lap(u, uzm_ref, uzp_ref)
                            - uvv + f * (1.0 - u))
    vo_ref[...] = v + dt * (dv * lap(v, vzm_ref, vzp_ref)
                            + uvv - (f + k) * v)


def pick_tz(shape) -> int:
    """Largest z-slab size fitting the VMEM budget (0 = does not fit)."""
    d, h, w = shape
    plane = h * w * 4
    for tz in (8, 4, 2, 1):
        if d % tz == 0 and (4 * tz + 4) * plane <= _VMEM_BUDGET:
            return tz
    return 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def step_pallas(u: jnp.ndarray, v: jnp.ndarray, params_vec: jnp.ndarray,
                interpret: bool = False):
    """One Gray-Scott step. ``params_vec = [f, k, du, dv, dt]`` (f32[5]).
    Requires ``pick_tz(u.shape) > 0``."""
    d, h, w = u.shape
    tz = pick_tz(u.shape)
    if tz == 0:
        raise ValueError(f"grid {u.shape} does not fit the VMEM budget")
    nb = d // tz

    slab = pl.BlockSpec((tz, h, w), lambda i: (i, 0, 0))
    # one-slice halo views of the same array; index_map is in units of the
    # (1, H, W) block shape, i.e. element rows, so periodic wrap is exact
    zm = pl.BlockSpec((1, h, w), lambda i: ((i * tz - 1) % d, 0, 0))
    zp = pl.BlockSpec((1, h, w), lambda i: (((i + 1) * tz) % d, 0, 0))

    return pl.pallas_call(
        _kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  slab, slab, zm, zp, zm, zp],
        out_specs=[slab, slab],
        out_shape=[jax.ShapeDtypeStruct((d, h, w), jnp.float32)] * 2,
        interpret=interpret,
    )(params_vec, u, v, u, u, v, v)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def multi_step_pallas(u, v, params_vec, n: int, interpret: bool = False):
    return jax.lax.fori_loop(
        0, n, lambda _, s: step_pallas(s[0], s[1], params_vec,
                                       interpret=interpret), (u, v))
