"""Pallas TPU kernel for the Gray-Scott reaction-diffusion step.

The XLA formulation (sim/grayscott.py) builds the 6-point Laplacian from
``jnp.roll`` — twelve materialized full-volume copies per step, ~3 ms at
256³ on a v5e (≈8× above memory-bound). This kernel fuses ``T`` whole
steps into a single pass: each grid step holds a ``[Tz + 2T, H, W]`` slab
of u and v in VMEM (the slab plus a T-slice halo on each z side, taken
from neighbor views of the same HBM arrays with periodic wrap in the
BlockSpec index_map), advances it T times entirely in VMEM — in-plane
neighbors by register shifts, z-halo validity shrinking by one slice per
step so the central Tz slices are exact — and writes the updated slab
once. Per T steps the volume is read ``(Tz+2T)/Tz`` ≈ 1.25× and written
1×, so HBM traffic per step drops by ~T× over the single-step kernel at
the cost of ``2T/Tz`` redundant stencil work.

Used by the single-device fast path only: the *sharded* simulation keeps
the roll formulation, where XLA lowers the rolls across a z-sharded mesh
to ICI halo collectives (see sim/grayscott.py docstring) — a Pallas kernel
with per-shard periodic wrap would silently corrupt shard boundaries.

On CPU the kernel runs in interpret mode (used by the parity test); the
production CPU path stays on the XLA formulation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# nominal bytes of live blocks per grid step; Mosaic double-buffers the
# pipelined inputs/outputs, so this must stay well under the part's VMEM.
# The figure is a HEURISTIC screen only — `fused_supported` /
# `multi_step_pallas` verify each (shape, T) choice with a one-time
# Mosaic compile probe and degrade to smaller T / the XLA roll path, so
# the budget's job is merely to skip probing hopeless candidates. The
# original 24 MB default silently pinned the 512^3 flagship to T=1
# (full 2 GB/step HBM traffic, ~20 GB of the measured 29 GB frame);
# 96 MB admits T=2/tz=4 (40 MB nominal) and lets the probe — not the
# heuristic — decide what this part's 128 MB VMEM really accepts.
_VMEM_BUDGET = int(os.environ.get("SITPU_STENCIL_VMEM_MB", "96")) \
    * 1024 * 1024

# (shape, t_steps) -> did Mosaic accept the fused kernel?
_PROBE_CACHE: dict = {}


def _compile_ok(shape, t_steps: int, tz: int = 0,
                with_ranges: bool = False) -> bool:
    """One-time probe: does the fused kernel at this (shape, T, tz)
    actually compile on the current TPU? A VMEM budget miss surfaces as a
    Mosaic resource-exhausted error at compile time — catch it HERE,
    where a fallback exists, not inside a traced frame step where it
    cannot be caught. Cached per process (and cheap on repeats via the
    persistent JAX compile cache). ``with_ranges`` probes the
    occupancy-ranges epilogue variant — a distinct kernel Mosaic may
    judge differently."""
    key = (tuple(shape), int(t_steps), int(tz), bool(with_ranges))
    ok = _PROBE_CACHE.get(key)
    if ok is None:
        try:
            s = jax.ShapeDtypeStruct(tuple(shape), jnp.float32)
            p = jax.ShapeDtypeStruct((5,), jnp.float32)
            step_pallas.lower(s, s, p, t_steps=t_steps, tz=tz,
                              with_ranges=with_ranges).compile()
            ok = True
        except Exception:
            ok = False
        _PROBE_CACHE[key] = ok
    return ok


def fused_supported(shape, t_steps: int = 1) -> bool:
    """Can a fused kernel (1D z-slab or 2D z×h tile) run this grid on the
    current backend? True iff some tile fits the nominal budget AND (on
    TPU) Mosaic accepts one of the capped-walk candidates. The gate
    `sim.grayscott.multi_step_fast` consults this before choosing the
    Pallas path; `_best_schedule` then picks the cheapest compiling
    schedule."""
    on_tpu = jax.default_backend() == "tpu"
    if not (tz_candidates(shape, t_steps)
            or tile2d_candidates(shape, t_steps)):
        return False
    if not on_tpu:
        return True          # interpret mode has no VMEM to exhaust
    return _best_schedule(shape, t_steps, True) is not None


def _roll(x: jnp.ndarray, shift: int, axis: int) -> jnp.ndarray:
    """Periodic shift via the Mosaic rotate primitive (a slice+concat
    formulation forces unaligned sublane/lane relayouts and is ~20x
    slower)."""
    return pltpu.roll(x, shift % x.shape[axis], axis)


def _kernel(t_steps, with_ranges, p_ref, u_ref, v_ref, uzm_ref, uzp_ref,
            vzm_ref, vzp_ref, uo_ref, vo_ref, *rng_refs):
    f, k, du, dv, dt = (p_ref[i] for i in range(5))
    t = t_steps
    u = jnp.concatenate([uzm_ref[...], u_ref[...], uzp_ref[...]], axis=0)
    v = jnp.concatenate([vzm_ref[...], v_ref[...], vzp_ref[...]], axis=0)

    def lap(x):
        # z neighbors by shift with edge replication: the outermost slice
        # of the halo goes stale anyway (validity shrinks 1 slice per
        # step from each end; after T steps the central Tz are exact)
        zm = jnp.concatenate([x[:1], x[:-1]], axis=0)
        zp = jnp.concatenate([x[1:], x[-1:]], axis=0)
        return (zm + zp
                + _roll(x, 1, 1) + _roll(x, -1, 1)
                + _roll(x, 1, 2) + _roll(x, -1, 2) - 6.0 * x)

    for _ in range(t):
        uvv = u * v * v
        u, v = (u + dt * (du * lap(u) - uvv + f * (1.0 - u)),
                v + dt * (dv * lap(v) + uvv - (f + k) * v))

    uo_ref[...] = u[t:u.shape[0] - t]
    vout = v[t:v.shape[0] - t]
    vo_ref[...] = vout
    if with_ranges:
        # occupancy epilogue: per-block min/max of the RENDERED field (v)
        # ride out of the pass as (1, 1) SMEM reductions — the slab is
        # already in VMEM, so the ranges cost no extra HBM traffic
        vlo_ref, vhi_ref = rng_refs
        vlo_ref[0, 0] = jnp.min(vout)
        vhi_ref[0, 0] = jnp.max(vout)


def tz_candidates(shape, t_steps: int = 1) -> tuple:
    """z-slab sizes for a T-step fused call fitting the VMEM budget and
    the divisibility constraints, largest first: tz | D so the grid tiles
    exactly, and T | tz so the T-slice halos are expressible as whole
    (T, H, W) blocks. The budget is a screen; the Mosaic compile probe
    (`_compile_ok`) is the authority, so `multi_step_pallas` walks this
    list until one compiles instead of betting everything on the
    nominal-largest choice."""
    d, h, w = shape
    plane = h * w * 4
    out = []
    for tz in (32, 16, 8, 4, 2, 1):
        if d % tz or tz % t_steps:
            continue
        # live VMEM: ~4 arrays (u, v and temporaries) of the haloed slab
        # plus the two output slabs
        if (4 * (tz + 2 * t_steps) + 2 * tz) * plane <= _VMEM_BUDGET:
            out.append(tz)
    return tuple(out)


def pick_tz(shape, t_steps: int = 1) -> int:
    """Largest nominally-fitting z-slab size (0 = none fits)."""
    cands = tz_candidates(shape, t_steps)
    return cands[0] if cands else 0


def _probe_pick(shape, t: int, cands, probe, interpret: bool):
    """Auto-pick walk shared by step_pallas/step_pallas2d: on TPU each
    budget-screened candidate must pass its Mosaic compile probe before
    being chosen (the screen is a heuristic; Mosaic is the authority —
    an unprobed auto-pick could hand a direct caller a compile-time
    resource error the production path would have degraded around)."""
    if not cands:
        raise ValueError(
            f"grid {shape} does not fit the VMEM budget at T={t}")
    if jax.default_backend() == "tpu" and not interpret:
        for c in cands:
            if probe(c):
                return c
        raise ValueError(
            f"Mosaic rejected every fused-stencil candidate for grid "
            f"{shape} at T={t} — use multi_step_pallas (degrades to "
            f"smaller T / the XLA roll path)")
    return cands[0]


@functools.partial(jax.jit, static_argnames=("t_steps", "interpret", "tz",
                                             "with_ranges"))
def step_pallas(u: jnp.ndarray, v: jnp.ndarray, params_vec: jnp.ndarray,
                t_steps: int = 1, interpret: bool = False, tz: int = 0,
                with_ranges: bool = False):
    """Advance ``t_steps`` Gray-Scott steps in one fused kernel pass.
    ``params_vec = [f, k, du, dv, dt]`` (f32[5]). Requires
    ``pick_tz(u.shape, t_steps) > 0``.

    Auto-pick contract (ADVICE r5 #4): ``tz=0`` walks the
    budget-screened `tz_candidates` and, on TPU, takes the first one the
    MOSAIC COMPILE PROBE accepts — the 96 MB ``_VMEM_BUDGET`` screen is a
    heuristic and must never be the last word, or a direct call would
    compile-crash inside a traced step where nothing can catch it. If no
    candidate compiles this raises ``ValueError`` at trace time (use
    `multi_step_pallas`, which degrades to smaller T / the XLA roll
    path, for a never-raises schedule). An EXPLICIT ``tz`` is taken on
    trust after the ``t_steps | tz | D`` shape check: it is NOT probed,
    so Mosaic resource errors surface to the caller at compile time —
    pass probe-validated values (`_best_schedule`) when that matters.

    ``with_ranges=True`` appends the occupancy epilogue (ops/occupancy):
    the return becomes ``(u', v', vlo, vhi)`` with per-z-slab min/max of
    the updated v field shaped ``[d // tz, 1]`` — DATA-layout brick
    ranges at the kernel's own granularity, normalized downstream by
    `occupancy.remap_ranges`."""
    d, h, w = u.shape
    t = t_steps
    if tz:
        # explicit tz: enforce the tz_candidates constraints instead of
        # silently leaving output tiles unwritten (grid floor-division)
        if d % tz or tz % t:
            raise ValueError(
                f"explicit tz={tz} violates T | tz | D for grid {u.shape} "
                f"at T={t} (need d % tz == 0 and tz % t_steps == 0)")
    else:
        tz = _probe_pick(u.shape, t, tz_candidates(u.shape, t),
                         lambda tz_: _compile_ok(u.shape, t, tz_,
                                                 with_ranges),
                         interpret)
    nb = d // tz
    nb_t = d // t                 # array length in halo-block units

    slab = pl.BlockSpec((tz, h, w), lambda i: (i, 0, 0))
    # T-slice halo views of the same arrays; index_map is in units of the
    # (T, H, W) block shape, so periodic wrap is exact (T | tz makes the
    # offsets whole blocks)
    r = tz // t
    zm = pl.BlockSpec((t, h, w), lambda i: ((i * r - 1) % nb_t, 0, 0))
    zp = pl.BlockSpec((t, h, w), lambda i: ((i + 1) * r % nb_t, 0, 0))

    out_specs = [slab, slab]
    out_shape = [jax.ShapeDtypeStruct((d, h, w), jnp.float32)] * 2
    if with_ranges:
        rng = pl.BlockSpec((1, 1), lambda i: (i, 0),
                           memory_space=pltpu.SMEM)
        out_specs += [rng, rng]
        out_shape += [jax.ShapeDtypeStruct((nb, 1), jnp.float32)] * 2

    return pl.pallas_call(
        functools.partial(_kernel, t, with_ranges),
        grid=(nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  slab, slab, zm, zp, zm, zp],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(params_vec, u, v, u, u, v, v)


def _multi_step_impl(u, v, params_vec, n: int, interpret: bool,
                     ranges_to):
    """Greedy multi-T schedule walk shared by `multi_step_pallas` and
    `multi_step_pallas_ranges`. ``ranges_to = (nzb, nyb)`` threads the
    occupancy epilogue through every pass: each kernel's native-
    granularity v ranges are normalized onto the fixed (nzb, nyb) brick
    grid (occupancy.remap_ranges) so the fori_loop carry keeps one shape
    across schedules; the LAST executed pass's ranges describe the final
    field, which is what the caller gets."""
    with_ranges = ranges_to is not None
    if with_ranges:
        from scenery_insitu_tpu.ops.occupancy import (field_ranges,
                                                      remap_ranges)
        nzb, nyb = ranges_to
        if n == 0:
            # no pass runs to overwrite the seed — a (+inf, -inf) seed
            # would gate every cell off under a band-pass TF; reduce
            # the field as-is instead (the render-only sim_steps=0 A/B)
            r = field_ranges(v, nzb, nyb)
            return (u, v, r.lo, r.hi)
        s = (u, v,
             jnp.full((nzb, nyb), jnp.inf, jnp.float32),
             jnp.full((nzb, nyb), -jnp.inf, jnp.float32))
    else:
        s = (u, v)
    remaining = n
    on_tpu = jax.default_backend() == "tpu" and not interpret
    for t in range(min(_FUSE_T, n), 0, -1):
        reps = remaining // t
        if reps == 0:
            continue
        sched = _best_schedule(u.shape, t, on_tpu, with_ranges)
        if sched is None:
            continue         # Mosaic rejected this T: degrade, don't die
        kind, tz, th = sched

        def one(s, t=t, kind=kind, tz=tz, th=th):
            if kind == "2d":
                out = step_pallas2d(s[0], s[1], params_vec, t,
                                    interpret=interpret, tz=tz, th=th,
                                    with_ranges=with_ranges)
            else:
                out = step_pallas(s[0], s[1], params_vec, t,
                                  interpret=interpret, tz=tz,
                                  with_ranges=with_ranges)
            if not with_ranges:
                return out
            un, vn, lo, hi = out
            return (un, vn) + remap_ranges(lo, hi, ranges_to)

        s = jax.lax.fori_loop(0, reps, lambda _, s: one(s), s)
        remaining -= reps * t
        if remaining == 0:
            break
    if remaining:   # pick_tz(shape, 1) == 0: caller should have gated
        raise ValueError(f"grid {u.shape} does not fit the VMEM budget")
    return s


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def multi_step_pallas(u, v, params_vec, n: int, interpret: bool = False):
    """n Gray-Scott steps, fused ``_FUSE_T`` at a time; the remainder runs
    at progressively smaller fusion factors (greedy decomposition, so e.g.
    n=5 runs one T=4 pass + one T=1 pass instead of silently degrading the
    whole loop to T=1)."""
    return _multi_step_impl(u, v, params_vec, n, interpret, None)


@functools.partial(jax.jit, static_argnames=("n", "nzb", "nyb",
                                             "interpret"))
def multi_step_pallas_ranges(u, v, params_vec, n: int, nzb: int, nyb: int,
                             interpret: bool = False):
    """`multi_step_pallas` with the occupancy epilogue: returns
    ``(u', v', vlo, vhi)`` where vlo/vhi are per-brick min/max of the
    FINAL v field on the (nzb, nyb) data-layout brick grid
    (ops/occupancy.FieldRanges arrays) — the per-frame empty-space
    structure rides out of the sim pass instead of costing a volume
    sweep. Gate availability with `ranges_supported` (the epilogue
    variant is a distinct kernel Mosaic may reject independently)."""
    return _multi_step_impl(u, v, params_vec, n, interpret, (nzb, nyb))


def ranges_supported(shape, t_steps: int = 1) -> bool:
    """Can the occupancy-ranges epilogue ride the fused stencil on this
    grid/backend? Checks the T=1 schedule (the greedy decomposition's
    catch-all, so `multi_step_pallas_ranges` cannot hit an uncovered
    remainder when it holds)."""
    on_tpu = jax.default_backend() == "tpu"
    if not (tz_candidates(shape, t_steps)
            or tile2d_candidates(shape, t_steps)):
        return False
    if not on_tpu:
        return True          # interpret mode compiles anything
    return _best_schedule(shape, 1, True, with_ranges=True) is not None


_FUSE_T = 4


# ------------------------------------------------- 2D-blocked (z x h) fusion
#
# At 512^3 a full (H, W) plane is 1 MB, so the z-only slab above cannot
# afford a useful T at any tz — the kernel's ~6 live haloed-slab copies
# exhaust VMEM (the round-5 flagship ran the sim at T=1: a full 2 GB of
# HBM traffic per step, ~20 GB of the measured 29 GB frame). Blocking z
# AND h shrinks the live set quadratically while the halo overhead stays
# linear in T, so T=4 fits 512^3 comfortably: per T steps the volume is
# read ((tz+2T)(th+2T))/(tz·th) ≈ 1.6x and written once — ~3x less HBM
# traffic per step than the best 1D schedule at this scale.
#
# Geometry: the T-step dependency cone of the 6-point Laplacian is an L1
# ball, covered by a square halo of width T in (z, h). Each field reads
# 9 views of the same HBM array (center + 4 edges + 4 corners, periodic
# wrap via index_map arithmetic in block units — requiring T | tz | D
# and T | th | H); in-kernel, rows of blocks are concatenated into one
# (tz+2T, th+2T, W) padded array. z and h neighbors use edge-replicated
# shifts (the replicated rim is exactly the region whose validity the
# per-step shrink discards); w neighbors keep the Mosaic rotate because
# w is the full, truly-periodic lane axis.


def _kernel2d(t_steps, with_ranges, p_ref,
              uc, un, us, uw, ue, unw, une, usw, use_,
              vc, vn, vs, vw, ve, vnw, vne, vsw, vse,
              uo_ref, vo_ref, *rng_refs):
    f, k, du, dv, dt = (p_ref[i] for i in range(5))
    t = t_steps

    def pad(n, w_, c, e, nw, ne, s, sw, se):
        top = jnp.concatenate([nw[...], n[...], ne[...]], axis=1)
        mid = jnp.concatenate([w_[...], c[...], e[...]], axis=1)
        bot = jnp.concatenate([sw[...], s[...], se[...]], axis=1)
        return jnp.concatenate([top, mid, bot], axis=0)

    u = pad(un, uw, uc, ue, unw, une, us, usw, use_)
    v = pad(vn, vw, vc, ve, vnw, vne, vs, vsw, vse)

    def lap(x):
        zm = jnp.concatenate([x[:1], x[:-1]], axis=0)
        zp = jnp.concatenate([x[1:], x[-1:]], axis=0)
        hm = jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)
        hp = jnp.concatenate([x[:, 1:], x[:, -1:]], axis=1)
        return (zm + zp + hm + hp
                + _roll(x, 1, 2) + _roll(x, -1, 2) - 6.0 * x)

    for _ in range(t):
        uvv = u * v * v
        u, v = (u + dt * (du * lap(u) - uvv + f * (1.0 - u)),
                v + dt * (dv * lap(v) + uvv - (f + k) * v))

    uo_ref[...] = u[t:u.shape[0] - t, t:u.shape[1] - t]
    vout = v[t:v.shape[0] - t, t:v.shape[1] - t]
    vo_ref[...] = vout
    if with_ranges:
        # occupancy epilogue (see _kernel): per-(tz, th)-block min/max
        # of the updated field, free of extra HBM traffic
        vlo_ref, vhi_ref = rng_refs
        vlo_ref[0, 0] = jnp.min(vout)
        vhi_ref[0, 0] = jnp.max(vout)


def tile2d_candidates(shape, t_steps: int = 1) -> tuple:
    """(tz, th) tiles for the 2D-blocked T-step kernel fitting the VMEM
    screen, best-first by modeled HBM traffic per step. Constraints:
    T | tz | D, T | th | H (halo/corner views are whole blocks of the
    halo shapes), and w stays whole (the periodic lane axis)."""
    d, h, w = shape
    t = t_steps
    cands = []
    for tz in (32, 16, 8, 4):
        if d % tz or tz % t:
            continue
        for th in (256, 128, 64, 32):
            if h % th or th % t:
                continue
            # ~6 live copies of the padded block (u, v, laplacian
            # temporaries) + the two output blocks
            live = (6 * (tz + 2 * t) * (th + 2 * t) + 2 * tz * th) * w * 4
            if live > _VMEM_BUDGET:
                continue
            # HBM traffic per step per field, in units of volume bytes:
            # (read amplification + 1 write) / T
            traffic = ((tz + 2 * t) * (th + 2 * t) / (tz * th) + 1.0) / t
            cands.append((traffic, tz, th))
    cands.sort()
    return tuple((tz, th) for _, tz, th in cands)


@functools.partial(jax.jit,
                   static_argnames=("t_steps", "interpret", "tz", "th",
                                    "with_ranges"))
def step_pallas2d(u, v, params_vec, t_steps: int = 1,
                  interpret: bool = False, tz: int = 0, th: int = 0,
                  with_ranges: bool = False):
    """Advance ``t_steps`` steps in one 2D-blocked fused pass.

    Same auto-pick contract as `step_pallas` (ADVICE r5 #4): ``(0, 0)``
    walks `tile2d_candidates` best-first and, on TPU, returns the first
    tile the Mosaic compile probe accepts — the VMEM budget is only a
    screen — raising ``ValueError`` at trace time when none compiles
    (`multi_step_pallas` is the degrading wrapper). An explicit
    ``(tz, th)`` must satisfy ``T | tz | D`` and ``T | th | H`` (the
    `tile2d_candidates` lattice) and is then taken on trust — unprobed,
    so Mosaic errors surface at compile time; route through
    `_best_schedule` for probe-validated tiles.

    ``with_ranges=True`` appends the occupancy epilogue: the return
    becomes ``(u', v', vlo, vhi)`` with per-(z, y)-block min/max of the
    updated v shaped ``[d // tz, h // th]`` (see `step_pallas`)."""
    d, h, w = u.shape
    t = t_steps
    if tz or th:
        # explicit tile: a value off the T | tz | D / T | th | H lattice
        # makes grid=(d//tz, h//th) floor-divide and silently leaves part
        # of the output unwritten — reject it loudly instead
        if not (tz and th):
            raise ValueError("pass both tz and th (or neither)")
        if d % tz or h % th or tz % t or th % t:
            raise ValueError(
                f"explicit (tz={tz}, th={th}) violates T | tz | D and "
                f"T | th | H for grid {u.shape} at T={t} (need d % tz == "
                f"0, h % th == 0, tz % t_steps == 0, th % t_steps == 0)")
    else:
        tz, th = _probe_pick(
            u.shape, t, tile2d_candidates(u.shape, t),
            lambda c: _compile2d_ok(u.shape, t, c[0], c[1], with_ranges),
            interpret)
    nzb, nhb = d // tz, h // th
    nz_t, nh_t = d // t, h // t    # array length in halo-block units
    rz, rh = tz // t, th // t

    c_ = pl.BlockSpec((tz, th, w), lambda i, j: (i, j, 0))
    # edge views in halo-block units (periodic wrap by modular index)
    n_ = pl.BlockSpec((t, th, w), lambda i, j: ((i * rz - 1) % nz_t, j, 0))
    s_ = pl.BlockSpec((t, th, w), lambda i, j: ((i + 1) * rz % nz_t, j, 0))
    w_ = pl.BlockSpec((tz, t, w), lambda i, j: (i, (j * rh - 1) % nh_t, 0))
    e_ = pl.BlockSpec((tz, t, w), lambda i, j: (i, (j + 1) * rh % nh_t, 0))
    nw = pl.BlockSpec((t, t, w),
                      lambda i, j: ((i * rz - 1) % nz_t,
                                    (j * rh - 1) % nh_t, 0))
    ne = pl.BlockSpec((t, t, w),
                      lambda i, j: ((i * rz - 1) % nz_t,
                                    (j + 1) * rh % nh_t, 0))
    sw = pl.BlockSpec((t, t, w),
                      lambda i, j: ((i + 1) * rz % nz_t,
                                    (j * rh - 1) % nh_t, 0))
    se = pl.BlockSpec((t, t, w),
                      lambda i, j: ((i + 1) * rz % nz_t,
                                    (j + 1) * rh % nh_t, 0))

    specs = [c_, n_, s_, w_, e_, nw, ne, sw, se]
    out_specs = [c_, c_]
    out_shape = [jax.ShapeDtypeStruct((d, h, w), jnp.float32)] * 2
    if with_ranges:
        rng = pl.BlockSpec((1, 1), lambda i, j: (i, j),
                           memory_space=pltpu.SMEM)
        out_specs += [rng, rng]
        out_shape += [jax.ShapeDtypeStruct((nzb, nhb), jnp.float32)] * 2
    return pl.pallas_call(
        functools.partial(_kernel2d, t, with_ranges),
        grid=(nzb, nhb),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + specs + specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(params_vec, *([u] * 9), *([v] * 9))


def _compile2d_ok(shape, t_steps: int, tz: int, th: int,
                  with_ranges: bool = False) -> bool:
    """Mosaic probe for the 2D kernel at (shape, T, tz, th); cached."""
    key = ("2d", tuple(shape), int(t_steps), int(tz), int(th),
           bool(with_ranges))
    ok = _PROBE_CACHE.get(key)
    if ok is None:
        try:
            s = jax.ShapeDtypeStruct(tuple(shape), jnp.float32)
            p = jax.ShapeDtypeStruct((5,), jnp.float32)
            step_pallas2d.lower(s, s, p, t_steps=t_steps,
                                tz=tz, th=th,
                                with_ranges=with_ranges).compile()
            ok = True
        except Exception:
            ok = False
        _PROBE_CACHE[key] = ok
    return ok


def modeled_sim_traffic(shape, n: int, fused: bool = True) -> float:
    """Modeled HBM bytes for ``n`` Gray-Scott steps under the schedules
    `multi_step_pallas` would pick (budget screen only — probe-free, so
    usable off-TPU), for the bench harness's traffic-model fallback and
    the per-lever A/B accounting. ``fused=False`` (or any remainder no
    fused schedule covers) charges the roll formulation's floor: one
    read + one write of u and v per step."""
    d, h, w = shape
    vol_bytes = 2 * 4.0 * d * h * w          # u + v, f32
    total = 0.0
    remaining = n
    if fused:
        for t in range(min(_FUSE_T, n), 0, -1):
            reps = remaining // t
            if reps == 0:
                continue
            sched = _best_schedule(shape, t, on_tpu=False)
            if sched is None:
                continue
            kind, tz, th = sched
            amp = ((tz + 2 * t) * (th + 2 * t) / (tz * th) if kind == "2d"
                   else (tz + 2 * t) / tz)
            total += reps * (amp + 1.0) * vol_bytes   # per T-step pass
            remaining -= reps * t
            if remaining == 0:
                break
    total += remaining * 2.0 * vol_bytes
    return total


def _best_schedule(shape, t: int, on_tpu: bool, with_ranges: bool = False):
    """Pick the cheapest compiling schedule for a T-step pass: 2D tiles
    and 1D slabs compete on modeled HBM traffic per step; the Mosaic
    probe (capped walk) has the final word. ``with_ranges`` probes the
    occupancy-epilogue kernel variant instead. Returns ("2d", tz, th),
    ("1d", tz, None) or None."""
    opts = []
    for tz, th in tile2d_candidates(shape, t)[:2]:
        traffic = ((tz + 2 * t) * (th + 2 * t) / (tz * th) + 1.0) / t
        opts.append((traffic, "2d", tz, th))
    for tz in tz_candidates(shape, t)[:2]:
        traffic = ((tz + 2 * t) / tz + 1.0) / t
        opts.append((traffic, "1d", tz, None))
    opts.sort(key=lambda o: o[0])
    for _, kind, tz, th in opts[:3]:
        if not on_tpu:
            return kind, tz, th
        ok = (_compile2d_ok(shape, t, tz, th, with_ranges) if kind == "2d"
              else _compile_ok(shape, t, tz, with_ranges))
        if ok:
            return kind, tz, th
    if opts:
        from scenery_insitu_tpu import obs

        # the auto-pick found budget-fitting candidates but Mosaic took
        # none — the caller runs this T-pass on the XLA roll path;
        # ledger-only (callers decide loudness via fused_supported)
        obs.degrade("sim.stencil_schedule", f"fused T={t}", "xla_roll",
                    f"Mosaic rejected all {len(opts[:3])} probed "
                    f"schedule candidates for grid {tuple(shape)}",
                    warn=False)
    return None
