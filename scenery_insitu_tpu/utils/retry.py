"""Bounded exponential backoff — the bench.py platform-retry pattern
(``min(base * 2**attempt, cap)``) extracted so every reconnecting
endpoint paces identically (docs/ROBUSTNESS.md "Liveness supervision").

Used by ``runtime/streaming.py`` (``VDISubscriber`` / ``SteeringEndpoint``
reconnects after a liveness deadline) and by ``bench.py`` between
platform attempts. Pure stdlib, no jax import — safe at module load from
anywhere.
"""

from __future__ import annotations


def backoff_delay(attempt: int, base_s: float = 0.5, cap_s: float = 30.0,
                  factor: float = 2.0) -> float:
    """Delay before retry ``attempt`` (0-based): ``base * factor**attempt``
    capped at ``cap_s``. Deterministic — chaos tests replay exactly."""
    if attempt < 0:
        attempt = 0
    return min(base_s * factor ** attempt, cap_s)


class Backoff:
    """Stateful wrapper: ``next_delay()`` walks the bounded exponential
    ladder, ``reset()`` (call on success / first sign of life) rewinds it
    to the base delay."""

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0,
                 factor: float = 2.0):
        if base_s <= 0 or cap_s < base_s or factor < 1.0:
            raise ValueError(
                f"need 0 < base_s <= cap_s and factor >= 1, got "
                f"base_s={base_s}, cap_s={cap_s}, factor={factor}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self.attempt = 0

    def next_delay(self) -> float:
        d = backoff_delay(self.attempt, self.base_s, self.cap_s,
                          self.factor)
        self.attempt += 1
        return d

    def reset(self) -> None:
        self.attempt = 0
