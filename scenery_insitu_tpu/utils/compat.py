"""Version-compat shims over JAX APIs that moved between releases.

ONE place that knows both spellings, imported everywhere (pipelines,
benchmarks, tests), so a JAX upgrade/downgrade is a one-file fix instead
of a grep across the tree:

- ``shard_map``: ``jax.shard_map`` (jax >= 0.8, ``check_vma=``) vs
  ``jax.experimental.shard_map.shard_map`` (older, ``check_rep=``). The
  shim exposes the NEW spelling (``check_vma``) and translates down.
- ``tpu_compiler_params``: ``pltpu.CompilerParams`` vs the older
  ``pltpu.TPUCompilerParams``.
- ``axis_size``: ``jax.lax.axis_size`` vs the ``psum(1, axis)`` idiom
  on JAX versions that predate it.
"""

from __future__ import annotations

import jax

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)
if _NEW_SHARD_MAP is None:
    from jax.experimental.shard_map import shard_map as _OLD_SHARD_MAP


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
    """``jax.shard_map`` with the new keyword surface on every supported
    JAX: ``check_vma`` maps onto the legacy ``check_rep`` (same meaning —
    skip the replication/varying-manual-axes output check)."""
    if _NEW_SHARD_MAP is not None:
        return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma,
                              **kw)
    return _OLD_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kw)


def axis_size(axis_name) -> int:
    """Size of a mapped mesh axis inside shard_map/pmap tracing."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def tpu_compiler_params(**kw):
    """Build Pallas TPU compiler params under either class name."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)
