"""Backend bootstrap helpers shared by every benchmark/driver entry point.

This environment registers an external TPU plugin ("axon") in every
interpreter and pins JAX_PLATFORMS to it; the plugin tunnels to one shared
chip and HANGS backend lookup when the tunnel is down — and setting
``JAX_PLATFORMS=cpu`` alone does NOT prevent the hang once the factory is
registered. Anything that wants a deterministic CPU (virtual-mesh) run
must both pin the platform and pop the factory, and anything that may run
after a backend already initialized must re-exec. One implementation here
instead of a copy per script."""

from __future__ import annotations

import os
import sys


def pin_cpu_backend() -> None:
    """Pin the current process to the CPU platform and neutralize the axon
    TPU shim. Must run before any JAX backend initializes (importing jax
    is fine; touching devices is not)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def virtual_mesh_env(n_devices: int, base: dict = None) -> dict:
    """Environment for a child process with an n-device virtual CPU mesh
    (the child must still call pin_cpu_backend() before JAX use)."""
    env = dict(base if base is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env


def enable_compile_cache() -> None:
    """Point JAX at a persistent compilation cache so repeat runs of the
    bench / dry-run entry points skip the ~25 s flagship compile.
    Default dir lives under the user's home (a /tmp path could be
    pre-created — squatted — by another local user, who would then own
    the dir the deserialized executables come from); $SITPU_JAX_CACHE
    overrides. Safe on any JAX version — silently a no-op where
    unsupported."""
    try:
        import jax

        default = os.path.join(
            os.path.expanduser("~"), ".cache", "sitpu_jax_cache")
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("SITPU_JAX_CACHE", default))
    except Exception:
        pass


def probe_tpu(timeout_s: int = None) -> int:
    """Device count of a LIVE TPU backend, else 0. Must be a subprocess
    with a hard timeout — a dead tunnel HANGS backend access instead of
    erroring — and validates a real matmul, not just device enumeration.
    ``timeout_s`` defaults to $SITPU_BENCH_PROBE_TIMEOUT or 150 (raise it
    on clusters with slow cold backend init)."""
    import subprocess

    if timeout_s is None:
        timeout_s = int(os.environ.get("SITPU_BENCH_PROBE_TIMEOUT", 150))
    code = ("import jax\n"
            "assert jax.devices()[0].platform == 'tpu'\n"
            "import jax.numpy as jnp\n"
            "assert float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()) > 0\n"
            "print(jax.device_count())\n")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           env=dict(os.environ), timeout=timeout_s,
                           stdout=subprocess.PIPE,
                           stderr=subprocess.DEVNULL)
        return int(p.stdout.strip() or 0) if p.returncode == 0 else 0
    except (subprocess.TimeoutExpired, ValueError):
        return 0


def reexec_virtual_mesh(n_devices: int, marker: str) -> None:
    """Replace this process with a copy running on an n-device virtual CPU
    mesh; ``marker`` is the env flag that breaks the recursion (the child
    sees it set and proceeds, calling pin_cpu_backend())."""
    env = virtual_mesh_env(n_devices)
    env[marker] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)
