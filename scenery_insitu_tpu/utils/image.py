"""Host-boundary image utilities (gamma, PNG, diff metrics).

Gamma is applied exactly once, here, at the host boundary (the reference
applied ``pow(v, 1/2.2)`` inside the generation shader,
VDIGenerator.comp:537 — one of the parity hazards SURVEY.md §7 flags)."""

from __future__ import annotations


import numpy as np


def to_display(image_chw: np.ndarray, gamma: float = 2.2,
               unpremultiply: bool = False) -> np.ndarray:
    """f32[4, H, W] premultiplied linear RGBA -> uint8[H, W, 4] display."""
    img = np.asarray(image_chw, np.float32)
    rgb, a = img[:3], img[3:4]
    if unpremultiply:
        rgb = rgb / np.maximum(a, 1e-6)
    rgb = np.clip(rgb, 0.0, 1.0) ** (1.0 / gamma)
    out = np.concatenate([rgb, np.clip(a, 0.0, 1.0)], axis=0)
    return (np.moveaxis(out, 0, -1) * 255.0 + 0.5).astype(np.uint8)


def save_png(path: str, image_chw: np.ndarray, gamma: float = 2.2) -> None:
    from PIL import Image
    Image.fromarray(to_display(np.asarray(image_chw), gamma)).save(path)


def psnr(a: np.ndarray, b: np.ndarray, peak: float = 1.0) -> float:
    mse = float(np.mean((np.asarray(a, np.float64) - np.asarray(b, np.float64)) ** 2))
    if mse == 0:
        return float("inf")
    return 10.0 * np.log10(peak * peak / mse)
