"""The scenario zoo — named, steered, benched workloads through ONE
in-situ pipeline (docs/SCENARIOS.md; ROADMAP item 5).

The reference system's whole point was serving many scenario families
through one in-situ renderer (PAPER.md §0: Gray-Scott reaction-
diffusion, vortex-in-cell flow, MD particle clouds). This registry
makes that first-class here: a `Scenario` names a simulation family,
the config overrides that select it, a per-frame STEERING hook (driven
through the same protocol a network viewer uses —
``runtime.session.steer_session``), and a bench recipe
(benchmarks/scenario_bench.py runs every registered scenario and ships
per-scenario ms/frame + parity artifacts; tests/test_scenarios.py runs
the tier-1 smokes). Promoting a demo sim to a scenario means exactly:
register it here with a smoke + bench entry.

Built-ins:

- ``gray_scott``  the flagship reaction-diffusion VDI pipeline, with a
                  TIME-VARYING multi-channel transfer function driven
                  over steering (a ``tf`` message per period —
                  the session recompiles-or-reuses keyed on TF
                  identity, so a cycling schedule pays k compiles for k
                  distinct looks).
- ``vortex``      the incompressible vortex-ring flow (|curl u|
                  rendered as a VDI), steered between two jet-ramp
                  transfer functions.
- ``hybrid``      the MULTI-VOLUME scene: the vortex grid field
                  composited with sort-first particle splats (passive
                  tracers) in one frame — the ops/hybrid.py path,
                  reachable by name.
- ``lennard_jones`` the MD particle cloud (sort-first sphere splats),
                  steered by a slow camera dolly.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence, Tuple

Steer = Callable[[object, int], Optional[dict]]


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One registered workload: config overrides select the sim family
    and tuned defaults, ``steering`` (optional) returns at most one
    steering-protocol message per frame (applied through
    `runtime.session.steer_session` — the exact path a network viewer's
    messages take), and ``bench`` is the recipe scenario_bench runs
    (size overrides + frame count, small enough for CPU CI)."""

    name: str
    description: str
    overrides: Tuple[str, ...] = ()
    steering: Optional[Steer] = None
    # bench recipe: extra overrides (sizes) + frames for one timed run
    bench_overrides: Tuple[str, ...] = ()
    bench_frames: int = 6
    # volume scenarios assert brick-permutation composite parity in the
    # bench artifact; particle scenarios have no brick decomposition
    brick_parity: bool = True


_REGISTRY: Dict[str, Scenario] = {}


def register(scn: Scenario) -> Scenario:
    if scn.name in _REGISTRY:
        raise ValueError(f"scenario {scn.name!r} already registered")
    _REGISTRY[scn.name] = scn
    return scn


def get(name: str) -> Scenario:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(registered: {names()})") from None


def names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def make_config(name: str, cfg=None, extra_overrides: Sequence[str] = ()):
    """FrameworkConfig of a scenario: its registered overrides applied
    over ``cfg`` (default FrameworkConfig), then ``extra_overrides``."""
    from scenery_insitu_tpu.config import FrameworkConfig

    scn = get(name)
    cfg = cfg or FrameworkConfig()
    return cfg.with_overrides(*scn.overrides, *extra_overrides)


def make_session(name: str, cfg=None, extra_overrides: Sequence[str] = (),
                 **session_kw):
    """Build an `InSituSession` running scenario ``name``."""
    from scenery_insitu_tpu.runtime.session import InSituSession

    return InSituSession(make_config(name, cfg, extra_overrides),
                         **session_kw)


def run_steered(sess, scn: Scenario, frames: int, fetch: bool = True):
    """Drive ``frames`` through ``sess`` with the scenario's steering
    hook injected per frame (in-process twin of the zmq drain — same
    `steer_session` consumer, so a hook message is indistinguishable
    from a network viewer's). Returns the last fetched payload."""
    from scenery_insitu_tpu.runtime.session import steer_session

    payload = {}
    for _ in range(frames):
        if scn.steering is not None:
            msg = scn.steering(sess, sess.frame_index)
            if msg:
                steer_session(sess, msg)
        out = sess.render_frame()
        if fetch:
            payload = sess._fetch(sess.frame_index - 1, out)
        sess.timers.frame_done()
    sess.timers.dump_totals()
    sess.obs.flush()
    return payload


def run(name: str, frames: int, cfg=None,
        extra_overrides: Sequence[str] = (), fetch: bool = True,
        **session_kw):
    """One-call scenario run: build the session, drive it steered."""
    scn = get(name)
    sess = make_session(name, cfg, extra_overrides, **session_kw)
    return run_steered(sess, scn, frames, fetch=fetch)


# ------------------------------------------------------- steering hooks


def tf_schedule(tf_messages: Sequence[dict], period: int) -> Steer:
    """Time-varying transfer function over steering: every ``period``
    frames the next prebuilt ``tf`` message fires (wrapping). Cycling
    through k distinct TFs exercises the session's recompile-or-reuse —
    after one full cycle every further update restores cached steps
    (``tf_steps_reused`` counter; docs/SCENARIOS.md)."""
    msgs = list(tf_messages)
    if not msgs or period < 1:
        raise ValueError("tf_schedule needs >= 1 message and period >= 1")

    def steer(sess, frame: int) -> Optional[dict]:
        if frame and frame % period == 0:
            return msgs[(frame // period) % len(msgs)]
        return None

    return steer


def camera_dolly(rate: float = 0.02) -> Steer:
    """Slow per-frame camera dolly toward the target — exercises the
    camera half of the steering protocol (every frame moves)."""
    import numpy as np

    def steer(sess, frame: int) -> Optional[dict]:
        eye = np.asarray(sess.camera.eye, np.float64)
        tgt = np.asarray(sess.camera.target, np.float64)
        eye = eye + (tgt - eye) * rate
        return {"type": "camera", "eye": [float(x) for x in eye]}

    return steer


def _tf_msgs(specs) -> list:
    from scenery_insitu_tpu.runtime.streaming import make_tf_message

    return [make_tf_message(points, colormap=cm) for points, cm in specs]


# ----------------------------------------------------------- built-ins

register(Scenario(
    name="gray_scott",
    description="Gray-Scott reaction-diffusion VDI pipeline (the "
                "flagship workload) with a time-varying multi-channel "
                "TF driven over steering",
    overrides=("sim.kind=gray_scott", "runtime.dataset=gray_scott"),
    steering=tf_schedule(_tf_msgs([
        ([(0.0, 0.0), (0.12, 0.0), (0.3, 0.12), (0.65, 0.3),
          (1.0, 0.5)], "viridis"),
        ([(0.0, 0.0), (0.2, 0.02), (0.5, 0.4), (1.0, 0.6)], "hot"),
    ]), period=4),
    bench_overrides=("sim.grid=[32,32,32]", "sim.steps_per_frame=2",
                     "render.width=64", "render.height=64"),
))

register(Scenario(
    name="vortex",
    description="Incompressible vortex-ring flow; |curl u| rendered as "
                "a VDI, steered between two jet-ramp TFs",
    overrides=("sim.kind=vortex", "runtime.dataset=vortex"),
    steering=tf_schedule(_tf_msgs([
        ([(0.0, 0.0), (0.15, 0.05), (1.0, 0.4)], "jet"),
        ([(0.0, 0.0), (0.4, 0.0), (0.7, 0.5), (1.0, 0.7)], "jet"),
    ]), period=3),
    bench_overrides=("sim.grid=[32,32,32]", "sim.steps_per_frame=1",
                     "render.width=64", "render.height=64"),
))

register(Scenario(
    name="hybrid",
    description="Multi-volume scene: vortex grid field + sort-first "
                "particle splats (passive tracers) composited in one "
                "frame (ops/hybrid.py)",
    overrides=("sim.kind=hybrid", "runtime.dataset=hybrid"),
    steering=tf_schedule(_tf_msgs([
        ([(0.0, 0.0), (0.2, 0.1), (1.0, 0.4)], "jet"),
    ]), period=4),
    bench_overrides=("sim.grid=[32,32,32]", "sim.num_particles=512",
                     "sim.steps_per_frame=1",
                     "render.width=64", "render.height=64"),
    brick_parity=False,   # hybrid builders ledger bricks inert
))

register(Scenario(
    name="lennard_jones",
    description="Lennard-Jones MD particle cloud (sort-first sphere "
                "splats), steered by a slow camera dolly",
    overrides=("sim.kind=lennard_jones",),
    steering=camera_dolly(0.02),
    bench_overrides=("sim.num_particles=2048", "sim.steps_per_frame=1",
                     "render.width=64", "render.height=64"),
    brick_parity=False,   # particle sessions have no volume bricks
))
