"""Self-contained H.264/AVC Annex-B bitstream writer (I_PCM mode) — the
real-H264 closure of the reference's video boundary
(DistributedVolumeRenderer.kt:275-291 streams H264/UDP; this image ships
no libx264/openh264/ffmpeg, so runtime probing falls back to mp4v for
cv2 sinks — README "Known gaps").

Every H.264 decoder must support the I_PCM macroblock mode (raw
uncompressed samples inside a standard slice), and an all-I_PCM stream
needs NONE of the codec's prediction/transform/entropy machinery: just
Exp-Golomb-coded SPS/PPS/slice headers, byte-aligned raw macroblocks,
and start-code emulation prevention. This module writes exactly that —
a conformant Baseline-profile elementary stream any player can decode,
losslessly carrying the (studio-range) YUV 4:2:0 frames. The price is
bitrate (~1.5 B/px — it is PCM), so this is the compatibility/archival
codec: cv2's mp4v/MJPEG sinks remain the compressed transport when
present, and a real libx264 upgrade drops in by replacing the writer.

Structure notes (ITU-T H.264 §7.3, Baseline):
- NAL: [start code] [1-byte header] [RBSP with 0x03 emulation bytes].
- SPS: profile 66, poc_type 2, frame_mbs_only; frame cropping trims the
  16-pixel macroblock padding back to the exact frame size.
- Every frame is an IDR with alternating idr_pic_id (consecutive IDRs
  must differ) — the stream is pure intra, seekable anywhere.
- I_PCM macroblock: mb_type ue(25), align to byte, then 256 luma +
  64 Cb + 64 Cr raw samples.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class BitWriter:
    """MSB-first bit packer for the (tiny) header parts of the stream."""

    def __init__(self):
        self.bytes = bytearray()
        self._acc = 0
        self._nbits = 0

    def u(self, value: int, bits: int) -> "BitWriter":
        for i in range(bits - 1, -1, -1):
            self._acc = (self._acc << 1) | ((value >> i) & 1)
            self._nbits += 1
            if self._nbits == 8:
                self.bytes.append(self._acc)
                self._acc = 0
                self._nbits = 0
        return self

    def ue(self, value: int) -> "BitWriter":
        """Unsigned Exp-Golomb."""
        v = value + 1
        nbits = v.bit_length()
        return self.u(v, 2 * nbits - 1)

    def se(self, value: int) -> "BitWriter":
        """Signed Exp-Golomb (0, 1, -1, 2, -2, ... -> 0, 1, 2, 3, 4)."""
        return self.ue(2 * value - 1 if value > 0 else -2 * value)

    def align_zero(self) -> "BitWriter":
        while self._nbits:
            self.u(0, 1)
        return self

    def raw(self, data: bytes) -> "BitWriter":
        assert self._nbits == 0, "raw bytes must be byte-aligned"
        self.bytes.extend(data)
        return self

    def rbsp_trailing(self) -> "BitWriter":
        self.u(1, 1)
        return self.align_zero()

    def getvalue(self) -> bytes:
        assert self._nbits == 0, "unterminated bitstring"
        return bytes(self.bytes)


def _emulation_prevent(rbsp: bytes) -> bytes:
    """Insert 0x03 after every 0x00 0x00 that precedes a byte <= 0x03
    (H.264 §7.4.1.1). Iterative scan — violations are rare in
    studio-range PCM (no 0x00 sample bytes), so each pass is cheap."""
    data = np.frombuffer(rbsp, np.uint8)
    out = []
    start = 0
    i = 0
    n = len(data)
    while i + 2 < n + 1:
        # vectorized jump to the next 00 00 pair at/after i
        z = (data[i:-1] == 0) & (data[i + 1:] == 0) if i < n - 1 else \
            np.zeros(0, bool)
        hits = np.nonzero(z)[0]
        if hits.size == 0:
            break
        j = i + int(hits[0])
        if j + 2 < n and data[j + 2] <= 3:
            out.append(data[start:j + 2].tobytes())
            out.append(b"\x03")
            start = j + 2
            i = j + 2
        else:
            i = j + 2 if j + 2 < n else n
    out.append(data[start:].tobytes())
    return b"".join(out)


def _nal(nal_type: int, rbsp: bytes, ref_idc: int = 3) -> bytes:
    return (b"\x00\x00\x00\x01" + bytes([(ref_idc << 5) | nal_type])
            + _emulation_prevent(rbsp))


def rgb_to_yuv420(rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
    """f32/u8 RGB [H, W, 3] (or [3, H, W]) -> studio-range BT.601 YUV
    4:2:0 (Y [H, W], Cb/Cr [H/2, W/2] u8). H and W must be even."""
    if rgb.ndim == 3 and rgb.shape[0] == 3:
        rgb = np.moveaxis(rgb, 0, -1)
    rgb = np.asarray(rgb, np.float32)
    if rgb.max() > 1.5:                    # u8-ranged input
        rgb = rgb / 255.0
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    y = 16.0 + 219.0 * (0.299 * r + 0.587 * g + 0.114 * b)
    cb = 128.0 + 224.0 * (-0.168736 * r - 0.331264 * g + 0.5 * b)
    cr = 128.0 + 224.0 * (0.5 * r - 0.418688 * g - 0.081312 * b)
    sub = lambda c: (c[0::2, 0::2] + c[0::2, 1::2] + c[1::2, 0::2]
                     + c[1::2, 1::2]) * 0.25
    clip = lambda c, hi: np.clip(np.rint(c), 16, hi).astype(np.uint8)
    return clip(y, 235), clip(sub(cb), 240), clip(sub(cr), 240)


# (level_idc, MaxFS macroblocks/frame) — ITU-T H.264 Table A-1; the
# signaled level must admit the frame size or strict decoders reject it
_LEVEL_MAXFS = ((10, 99), (11, 396), (21, 792), (22, 1620), (31, 3600),
                (32, 5120), (40, 8192), (42, 8704), (50, 22080),
                (51, 36864))


class H264IPCMWriter:
    """All-intra I_PCM H.264 elementary-stream writer.

    >>> w = H264IPCMWriter(width, height, fps=30.0)
    >>> stream = w.headers() + w.encode_frame(y, cb, cr) + ...
    """

    def __init__(self, width: int, height: int,
                 level_idc: Optional[int] = None, fps: float = 30.0):
        if width % 2 or height % 2:
            raise ValueError("H.264 4:2:0 needs even frame dimensions")
        self.width = width
        self.height = height
        self.mb_w = -(-width // 16)
        self.mb_h = -(-height // 16)
        if level_idc is None:
            mbs = self.mb_w * self.mb_h
            level_idc = next((lv for lv, maxfs in _LEVEL_MAXFS
                              if maxfs >= mbs), None)
            if level_idc is None:
                raise ValueError(
                    f"{width}x{height} ({mbs} MBs) exceeds level 5.1's "
                    "frame-size limit")
        self.level_idc = level_idc
        self.fps = float(fps)
        self._idr_flip = 0

    # ------------------------------------------------------------ headers

    def sps(self) -> bytes:
        w = BitWriter()
        w.u(66, 8)                          # profile_idc: Baseline
        w.u(0, 8)                           # constraint flags + reserved
        w.u(self.level_idc, 8)
        w.ue(0)                             # seq_parameter_set_id
        w.ue(0)                             # log2_max_frame_num_minus4
        w.ue(2)                             # pic_order_cnt_type
        w.ue(0)                             # max_num_ref_frames
        w.u(0, 1)                           # gaps_in_frame_num allowed
        w.ue(self.mb_w - 1)                 # pic_width_in_mbs_minus1
        w.ue(self.mb_h - 1)                 # pic_height_in_map_units_m1
        w.u(1, 1)                           # frame_mbs_only_flag
        w.u(1, 1)                           # direct_8x8_inference_flag
        crop_r = (self.mb_w * 16 - self.width) // 2
        crop_b = (self.mb_h * 16 - self.height) // 2
        if crop_r or crop_b:
            w.u(1, 1)                       # frame_cropping_flag
            w.ue(0).ue(crop_r).ue(0).ue(crop_b)
        else:
            w.u(0, 1)
        # VUI with timing only, so players honor the requested fps
        # (field-based ticks: fps = time_scale / (2 * num_units_in_tick))
        w.u(1, 1)                           # vui_parameters_present_flag
        w.u(0, 1)                           # aspect_ratio_info_present
        w.u(0, 1)                           # overscan_info_present
        w.u(0, 1)                           # video_signal_type_present
        w.u(0, 1)                           # chroma_loc_info_present
        w.u(1, 1)                           # timing_info_present_flag
        w.u(1000, 32)                       # num_units_in_tick
        w.u(max(1, int(round(self.fps * 2000.0))), 32)  # time_scale
        w.u(1, 1)                           # fixed_frame_rate_flag
        w.u(0, 1)                           # nal_hrd_parameters_present
        w.u(0, 1)                           # vcl_hrd_parameters_present
        w.u(0, 1)                           # pic_struct_present_flag
        w.u(0, 1)                           # bitstream_restriction_flag
        w.rbsp_trailing()
        return _nal(7, w.getvalue())

    def pps(self) -> bytes:
        w = BitWriter()
        w.ue(0)                             # pic_parameter_set_id
        w.ue(0)                             # seq_parameter_set_id
        w.u(0, 1)                           # entropy_coding_mode: CAVLC
        w.u(0, 1)                           # bottom_field_poc_present
        w.ue(0)                             # num_slice_groups_minus1
        w.ue(0).ue(0)                       # num_ref_idx_l0/l1_minus1
        w.u(0, 1)                           # weighted_pred_flag
        w.u(0, 2)                           # weighted_bipred_idc
        w.se(0)                             # pic_init_qp_minus26
        w.se(0)                             # pic_init_qs_minus26
        w.se(0)                             # chroma_qp_index_offset
        w.u(0, 1)                           # deblocking_control_present
        w.u(0, 1)                           # constrained_intra_pred
        w.u(0, 1)                           # redundant_pic_cnt_present
        w.rbsp_trailing()
        return _nal(8, w.getvalue())

    def headers(self) -> bytes:
        return self.sps() + self.pps()

    # ------------------------------------------------------------- frames

    def _pad(self, plane: np.ndarray, mb: int) -> np.ndarray:
        ph, pw = self.mb_h * mb, self.mb_w * mb
        return np.pad(plane, ((0, ph - plane.shape[0]),
                              (0, pw - plane.shape[1])), mode="edge")

    def encode_frame(self, y: np.ndarray, cb: np.ndarray, cr: np.ndarray
                     ) -> bytes:
        """One IDR access unit from studio-range planes (Y [H, W],
        Cb/Cr [H/2, W/2], u8). Returns the Annex-B NAL bytes."""
        if y.shape != (self.height, self.width):
            raise ValueError(f"luma shape {y.shape} != "
                             f"{(self.height, self.width)}")
        yp = self._pad(np.asarray(y, np.uint8), 16)
        cbp = self._pad(np.asarray(cb, np.uint8), 8)
        crp = self._pad(np.asarray(cr, np.uint8), 8)

        w = BitWriter()
        # slice_header (IDR, I slice)
        w.ue(0)                             # first_mb_in_slice
        w.ue(7)                             # slice_type: I (all slices)
        w.ue(0)                             # pic_parameter_set_id
        w.u(0, 4)                           # frame_num (log2 max = 4 bits)
        w.ue(self._idr_flip)                # idr_pic_id
        self._idr_flip ^= 1                 # consecutive IDRs must differ
        # dec_ref_pic_marking (IDR form)
        w.u(0, 1)                           # no_output_of_prior_pics
        w.u(0, 1)                           # long_term_reference_flag
        w.se(0)                             # slice_qp_delta
        # slice_data: raster-order I_PCM macroblocks
        for my in range(self.mb_h):
            for mx in range(self.mb_w):
                w.ue(25)                    # mb_type: I_PCM
                w.align_zero()              # pcm_alignment_zero_bit(s)
                w.raw(yp[my * 16:(my + 1) * 16,
                         mx * 16:(mx + 1) * 16].tobytes())
                w.raw(cbp[my * 8:(my + 1) * 8,
                          mx * 8:(mx + 1) * 8].tobytes())
                w.raw(crp[my * 8:(my + 1) * 8,
                          mx * 8:(mx + 1) * 8].tobytes())
        w.rbsp_trailing()
        return _nal(5, w.getvalue())

    def encode_rgb(self, rgb: np.ndarray) -> bytes:
        return self.encode_frame(*rgb_to_yuv420(rgb))


def h264_sink(path: str, gamma: float = 2.2, fps: float = 30.0):
    """Frame sink writing a raw .h264 Annex-B elementary stream via the
    I_PCM writer — the always-available real-H264 movie sink (players:
    `ffplay out.h264`, VLC, mpv; fps is signaled via SPS VUI timing).
    Call with f32[4|3, H, W] CHW (premultiplied session payloads) or
    [H, W, 3] HWC frames; `close()` (or use as a context manager)
    finishes the file."""

    class _Sink:
        def __init__(self):
            self.writer: Optional[H264IPCMWriter] = None
            self.f = open(path, "wb")
            self.frames = 0
            self.codec = "h264_ipcm"

        def __call__(self, frame: np.ndarray, meta=None) -> None:
            img = np.asarray(frame)
            if img.ndim != 3:
                raise ValueError(f"expected a 3-d frame, got {img.shape}")
            if img.shape[0] in (3, 4) and img.shape[-1] not in (3, 4):
                img = np.moveaxis(img[:3], 0, -1)      # CHW -> HWC
            elif img.shape[-1] == 4:
                img = img[..., :3]
            elif img.shape[-1] != 3:
                raise ValueError(f"no 3/4-channel axis in {img.shape}")
            img = np.clip(img, 0.0, 1.0) ** (1.0 / gamma)
            h, we = img.shape[0] & ~1, img.shape[1] & ~1
            img = img[:h, :we]
            if self.writer is None:
                self.writer = H264IPCMWriter(we, h, fps=fps)
                self.f.write(self.writer.headers())
            self.f.write(self.writer.encode_rgb(img))
            self.frames += 1

        def close(self) -> None:
            if not self.f.closed:
                self.f.close()

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.close()

    return _Sink()
