from scenery_insitu_tpu.io.vdi_io import (  # noqa: F401
    compress, decompress, load_vdi, pack_vdi_segments, save_vdi,
    unpack_vdi_segments)
