"""ctypes binding for the native LZ4 block codec (ingest/native/
lz4_block.cpp) — the fast-codec point of the reference's VDI wire path
(VDICompositingTest.kt:251-304, VDICompressionBenchmarks.kt:23-372)
that zstd cannot reach: LZ4's decode is a near-memcpy, which is what a
per-frame decompress-on-receive hop wants.

Blob layout: 8-byte little-endian uncompressed size, then the raw LZ4
block stream (the block format itself does not carry the size; the
reference sent per-segment byte counts alongside for the same reason).
Empty payloads are the 8-byte header alone.
"""

from __future__ import annotations

import ctypes
import os

# SITPU_NATIVE_BUILD: same build-variant switch as ingest/shm.py (the
# ASan CI job points both bindings at the instrumented build dir)
_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ingest", "native",
    os.environ.get("SITPU_NATIVE_BUILD", "build"), "liblz4block.so")

_lib = None


def _load():
    global _lib
    if _lib is None:
        from scenery_insitu_tpu.ingest.shm import ensure_built

        ensure_built()                      # same Makefile builds the codec
        lib = ctypes.CDLL(_LIB_PATH)
        for name in ("lz4b_compress", "lz4b_decompress"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
        lib.lz4b_bound.restype = ctypes.c_uint64
        lib.lz4b_bound.argtypes = [ctypes.c_uint64]
        _lib = lib
    return _lib


def available() -> bool:
    """Can the native codec be built/loaded here? (Needs g++.)"""
    try:
        _load()
        return True
    except Exception:
        return False


def compress(data: bytes) -> bytes:
    lib = _load()
    n = len(data)
    header = n.to_bytes(8, "little")
    if n == 0:
        return header
    cap = int(lib.lz4b_bound(n))
    out = (ctypes.c_uint8 * cap)()
    written = lib.lz4b_compress(data, n, out, cap)
    if written == 0:
        raise OSError(f"lz4 compression failed for {n}-byte payload")
    return header + ctypes.string_at(out, written)


def decompress(blob: bytes) -> bytes:
    lib = _load()
    if len(blob) < 8:
        raise ValueError("lz4 blob shorter than its size header")
    n = int.from_bytes(blob[:8], "little")
    if n == 0:
        if len(blob) != 8:
            raise ValueError("empty lz4 payload with trailing bytes")
        return b""
    # the header is untrusted wire data: bound the allocation by the
    # format's maximum expansion (~255x per match-run byte) before
    # committing n bytes — the native decoder's own checks run after
    if n > (len(blob) - 8) * 255 + 16:
        raise ValueError(
            f"corrupt lz4 blob: header claims {n} bytes from "
            f"{len(blob) - 8} compressed — exceeds format max expansion")
    out = (ctypes.c_uint8 * n)()
    got = lib.lz4b_decompress(blob[8:], len(blob) - 8, out, n)
    if got != n:
        raise ValueError(
            f"corrupt lz4 blob: header says {n} bytes, decoder produced "
            f"{got}")
    return ctypes.string_at(out, n)
