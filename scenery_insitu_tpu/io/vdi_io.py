"""VDI serialization, artifact checkpoints and wire compression
(SURVEY.md §7 step 10a).

≅ the reference's ``VDIDataIO.write/read`` metadata dumps + raw buffer dumps
(DistributedVolumes.kt:846-851, 910-915) that its offline renderers and the
distributed compositing benchmark replay as fixtures (VDICompositingTest.kt:
162-163) — the de-facto golden-file test strategy (SURVEY.md §4.2). One
``.npz`` holds both buffers and the full metadata pytree, so a single file
is a complete render-product checkpoint.

Wire compression mirrors the reference's per-segment variable-length
all-to-all (``distributeVDIsWithVariableLength`` with per-rank byte-limit
arrays ≅ MPI_Alltoallv, VDICompositingTest.kt:251-304): a VDI is split into
N column segments, each compressed independently, with the byte counts
("limits") carried alongside. Over ICI this is unnecessary (collectives are
uncompressed XLA ops); it exists for the DCN/host hop and for disk/network
streaming.

Codecs: the reference benchmarks LZ4/Snappy/LZMA/Gzip (
VDICompressionBenchmarks.kt); here "lz4" is a vendored clean-room LZ4
block codec (ingest/native/lz4_block.cpp, the reference's actual wire
family), plus zstandard, zlib and lzma — "none" passes through.
"""

from __future__ import annotations

import io
import os
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata

_META_FIELDS = VDIMetadata._fields


# ------------------------------------------------------------------ codecs

def _zstd():
    import zstandard
    return zstandard


CODECS = {
    "none": (lambda b, level: b, lambda b: b),
    "zlib": (lambda b, level: zlib.compress(b, level if level >= 0 else 6),
             zlib.decompress),
    "zstd": (lambda b, level: _zstd().ZstdCompressor(
                 level=level if level >= 0 else 3).compress(b),
             lambda b: _zstd().ZstdDecompressor().decompress(b)),
}


def _lzma_codec():
    import lzma
    return (lambda b, level: lzma.compress(b, preset=level if level >= 0 else 1),
            lzma.decompress)


CODECS["lzma"] = _lzma_codec()


def _lz4_enc(b, level):
    from scenery_insitu_tpu.io import lz4   # builds the native codec lazily

    return lz4.compress(b)


def _lz4_dec(b):
    from scenery_insitu_tpu.io import lz4

    return lz4.decompress(b)


# the reference's actual wire-codec family: LZ4 block format, vendored in
# ingest/native/lz4_block.cpp (level has no effect — LZ4's speed IS its
# parameter point). First use builds the .so; without a C++ toolchain the
# build error propagates from ensure_built.
CODECS["lz4"] = (_lz4_enc, _lz4_dec)


_ZSTD_OK = None


def have_zstd() -> bool:
    """Is the optional zstandard package importable? Cached."""
    global _ZSTD_OK
    if _ZSTD_OK is None:
        try:
            import zstandard  # noqa: F401
            _ZSTD_OK = True
        except ImportError:
            _ZSTD_OK = False
    return _ZSTD_OK


def resolve_codec(codec: str) -> str:
    """Degrade the default "zstd" to stdlib "zlib" (one warning) when the
    optional zstandard package is missing — an in-situ dump/stream must
    not die because of an absent compression extra. Applied at the
    WRITER entry points (save_vdi, pack_vdi_segments, VDIPublisher), and
    at unpack for symmetry; raw compress()/decompress() stay strict —
    data already written as zstd genuinely needs the module."""
    if codec == "zstd" and not have_zstd():
        from scenery_insitu_tpu import obs

        # ledger + the same one-time warning the inline site emitted
        obs.degrade("io.vdi_codec", "zstd", "zlib",
                    "zstandard is not installed (install zstandard for "
                    "the default codec)", stacklevel=3)
        return "zlib"
    return codec


def compress(data: bytes, codec: str = "zstd", level: int = -1) -> bytes:
    """level = -1 picks each codec's default."""
    try:
        enc, _ = CODECS[codec]
    except KeyError:
        raise ValueError(f"unknown codec {codec!r}; have {sorted(CODECS)}") from None
    return enc(data, level)


def decompress(data: bytes, codec: str = "zstd") -> bytes:
    try:
        _, dec = CODECS[codec]
    except KeyError:
        raise ValueError(f"unknown codec {codec!r}; have {sorted(CODECS)}") from None
    return dec(data)


# ----------------------------------------------------------- file artifacts

def save_vdi(path: str, vdi: VDI, meta: Optional[VDIMetadata] = None,
             codec: str = "zstd", precision: str = "f32",
             tile: Optional[Tuple[int, int, int]] = None,
             workers: int = 1) -> int:
    """Write a VDI (+ metadata) as one .npz artifact; returns bytes written.

    ``workers > 1`` compresses the large members (color, depth) on a
    thread pool — each member's blob is byte-identical to the serial
    path (per-member compress calls are independent), only the wall
    clock changes; used by the async delivery plane's disk sinks
    (docs/PERF.md "Async delivery").

    The npz members are individually compressed with ``codec`` (numpy's own
    deflate is off) so load/save round-trips are bit-exact and fast.

    ``precision="qpack8"`` runs the sort-last wire quantizer
    (ops.wire.qpack8_quantize_np; docs/PERF.md "Wire formats") as a
    PRE-codec pass: the buffers shrink 4× (u8 color / u8×2 depth against
    the stored [near, far]) before zstd/zlib even sees them, +inf empty
    slots round-trip exactly through the 0xFFFF sentinel, and the tag is
    recorded both in the artifact and in the metadata's ``precision``
    field so ``load_vdi`` dequantizes back to f32 transparently. Lossy by
    the wire contract — quantization error, not codec error.

    ``tile=(index, total, col0)`` marks a PARTIAL-frame column-block
    artifact (the tile-wave delivery unit, docs/PERF.md "Tile waves"):
    this VDI holds columns [col0, col0 + width) of tile ``index`` of
    ``total``. Read the placement back with ``load_vdi_tile``;
    ``load_vdi`` ignores it (the buffers are a self-contained VDI either
    way).
    """
    if precision not in ("f32", "qpack8"):
        raise ValueError(f"precision must be 'f32' or 'qpack8', "
                         f"got {precision!r}")
    codec = resolve_codec(codec)
    if precision == "qpack8":
        from scenery_insitu_tpu.ops.wire import (WIRE_CODES,
                                                 qpack8_quantize_np)

        qc, qd, near, far = qpack8_quantize_np(np.asarray(vdi.color),
                                               np.asarray(vdi.depth))
        members = {"color": qc, "depth": qd,
                   "__precision__": np.frombuffer(precision.encode(),
                                                  np.uint8),
                   "__qscale__": np.asarray([near, far], np.float32),
                   "__codec__": np.frombuffer(codec.encode(), np.uint8)}
        if meta is not None:
            meta = meta._replace(
                precision=np.int32(WIRE_CODES[precision]))
    else:
        members = {"color": np.asarray(vdi.color),
                   "depth": np.asarray(vdi.depth),
                   "__codec__": np.frombuffer(codec.encode(), np.uint8)}
        if meta is not None:
            # stamp what THIS artifact holds — a meta that rode in from a
            # quantized hop (load_vdi / VDISubscriber keep the tag as
            # provenance) must not mislabel the f32 buffers written here
            meta = meta._replace(precision=np.int32(0))
    if tile is not None:
        members["__tile__"] = np.asarray(tile, np.int64)    # (idx, total, col0)
    if meta is not None:
        for f in _META_FIELDS:
            members[f"meta_{f}"] = np.asarray(getattr(meta, f))
    buf = io.BytesIO()
    packed = {}
    big = [k for k, v in members.items()
           if not k.startswith("__") and v.nbytes >= 1024]
    if workers > 1 and len(big) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(workers,
                                                len(big))) as pool:
            blobs = dict(zip(big, pool.map(
                lambda k: compress(members[k].tobytes(), codec), big)))
    else:
        blobs = {k: compress(members[k].tobytes(), codec) for k in big}
    for k, v in members.items():
        if k not in blobs:
            packed[k] = v
        else:
            packed[k] = np.frombuffer(blobs[k], np.uint8)
            packed[f"__shape__{k}"] = np.asarray(v.shape, np.int64)
            packed[f"__dtype__{k}"] = np.frombuffer(
                str(v.dtype).encode(), np.uint8)
    np.savez(buf, **packed)
    data = buf.getvalue()
    with open(path, "wb") as f:
        f.write(data)
    return len(data)


def load_vdi(path: str) -> Tuple[VDI, Optional[VDIMetadata]]:
    """Inverse of ``save_vdi``. Honors the artifact's precision tag: a
    ``qpack8``-quantized dump is dequantized back to f32 here, so every
    reader sees the in-memory f32 convention regardless of how the bytes
    were stored. Artifacts from before the precision tag load with the
    f32 default."""
    with np.load(path) as z:
        codec = bytes(z["__codec__"]).decode() if "__codec__" in z else "none"
        precision = (bytes(z["__precision__"]).decode()
                     if "__precision__" in z else "f32")

        def member(k):
            if f"__shape__{k}" in z:
                raw = decompress(bytes(z[k]), codec)
                dtype = np.dtype(bytes(z[f"__dtype__{k}"]).decode())
                return np.frombuffer(raw, dtype).reshape(z[f"__shape__{k}"])
            return z[k]

        color, depth = member("color"), member("depth")
        if precision == "qpack8":
            from scenery_insitu_tpu.ops.wire import qpack8_dequantize_np

            near, far = (float(x) for x in z["__qscale__"])
            color, depth = qpack8_dequantize_np(color, depth, near, far)
        vdi = VDI(color, depth)
        if "meta_projection" in z:
            # pre-tag artifacts carry no meta_precision member — default 0
            meta = VDIMetadata(*[member(f"meta_{f}") if f"meta_{f}" in z
                                 else np.int32(0)
                                 for f in _META_FIELDS])
        else:
            meta = None
    return vdi, meta


def load_vdi_tile(path: str) -> Tuple[VDI, Optional[VDIMetadata],
                                      Optional[Tuple[int, int, int]]]:
    """`load_vdi` plus the artifact's tile placement: returns (vdi, meta,
    (tile_index, tiles_total, col0) or None for whole-frame artifacts).
    The reassembly contract: concatenating the ``tiles_total`` tiles of
    one frame along the width axis in tile order reproduces the frame
    the waves schedule composited."""
    vdi, meta = load_vdi(path)
    with np.load(path) as z:
        tile = (tuple(int(x) for x in z["__tile__"])
                if "__tile__" in z else None)
    return vdi, meta, tile


# ------------------------------------------------- temporal-delta records

def pack_delta_blobs(rec, codec: str = "zstd", level: int = -1
                     ) -> Tuple[dict, bytes, bytes]:
    """Serialize one ``ops/delta.DeltaRecord`` into the VDI stream's
    3-part wire convention (docs/PERF.md "Temporal deltas"): returns
    ``(header_fields, color_blob, depth_blob)`` where ``header_fields``
    is the ``delta`` header dict (mode/gen/base + the P residual's run
    and value counts, needed to re-split the blobs) and the blobs are
    codec-compressed payload bytes — full code arrays for I, the
    concatenated ``starts | lengths | values`` residual streams for P,
    empty for SKIP. The CRC/byte-count validation contract is unchanged:
    checksums are of these wire blobs."""
    codec = resolve_codec(codec)
    h = {"mode": rec.mode, "gen": int(rec.gen), "base": int(rec.base_gen)}
    if rec.mode == "I":
        cblob = compress(rec.c_payload[0].tobytes(), codec, level)
        dblob = compress(rec.d_payload[0].tobytes(), codec, level)
    elif rec.mode == "P":
        cs, cl, cv = rec.c_payload
        ds, dl, dv = rec.d_payload
        h.update(c_runs=int(cs.size), c_n=int(cv.size),
                 d_runs=int(ds.size), d_n=int(dv.size))
        cblob = compress(cs.tobytes() + cl.tobytes() + cv.tobytes(),
                         codec, level)
        dblob = compress(ds.tobytes() + dl.tobytes() + dv.tobytes(),
                         codec, level)
    elif rec.mode == "SKIP":
        cblob = dblob = b""
    else:
        raise ValueError(f"unknown delta mode {rec.mode!r}")
    return h, cblob, dblob


def delta_expected_bytes(dh: dict, cshape: Tuple[int, ...],
                         dshape: Tuple[int, ...]) -> Tuple[int, int]:
    """Decompressed byte counts a delta message's blobs must have —
    the shape-vs-bytes validation twin of the full-frame path (the
    declared ``color_shape``/``depth_shape`` always describe the FULL
    tile, so reconstruction and assembly stay shape-stable)."""
    mode = dh.get("mode")
    if mode == "I":
        return (int(np.prod(cshape)) * 4, int(np.prod(dshape)) * 2)
    if mode == "P":
        return (int(dh["c_runs"]) * 8 + int(dh["c_n"]) * 4,
                int(dh["d_runs"]) * 8 + int(dh["d_n"]) * 2)
    if mode == "SKIP":
        return 0, 0
    raise ValueError(f"unknown delta mode {mode!r}")


def unpack_delta_payload(dh: dict, craw: bytes, draw: bytes,
                         cshape: Tuple[int, ...], dshape: Tuple[int, ...]
                         ) -> Tuple[tuple, tuple]:
    """Inverse of `pack_delta_blobs` (after decompression + byte-count
    validation): returns the ``(c_payload, d_payload)`` tuples
    ``ops/delta.DeltaDecoder.apply`` consumes."""
    mode = dh["mode"]
    if mode == "SKIP":
        return (), ()
    if mode == "I":
        return ((np.frombuffer(craw, np.uint32).reshape(cshape),),
                (np.frombuffer(draw, np.uint16).reshape(dshape),))
    if mode != "P":
        raise ValueError(f"unknown delta mode {mode!r}")

    def split(raw, runs, n, vdtype):
        b = np.frombuffer(raw, np.uint8)
        starts = b[:runs * 4].view(np.uint32)
        lengths = b[runs * 4:runs * 8].view(np.uint32)
        values = b[runs * 8:].view(vdtype)
        if values.size != n:
            raise ValueError(f"residual carries {values.size} values, "
                             f"header declares {n}")
        return starts, lengths, values

    return (split(craw, int(dh["c_runs"]), int(dh["c_n"]), np.uint32),
            split(draw, int(dh["d_runs"]), int(dh["d_n"]), np.uint16))


# ------------------------------------------------- variable-length segments

def pack_vdi_segments(vdi: VDI, n: int, codec: str = "zstd",
                      level: int = -1) -> Tuple[List[bytes], np.ndarray,
                                                np.ndarray]:
    """Split a VDI into ``n`` column segments and compress each
    independently -> (blobs [2n: color0..colorN-1, depth0..], color_limits
    i64[n], depth_limits i64[n]) — the variable-length collective wire
    format (≅ colorLimits/depthLimits IntArrays,
    VDICompositingTest.kt:87-91,251-304)."""
    codec = resolve_codec(codec)
    k, _, h, w = vdi.color.shape
    if w % n:
        raise ValueError(f"width {w} not divisible into {n} segments")
    color = np.asarray(vdi.color)
    depth = np.asarray(vdi.depth)
    cs = np.split(color, n, axis=-1)
    ds = np.split(depth, n, axis=-1)
    cblobs = [compress(np.ascontiguousarray(c).tobytes(), codec, level)
              for c in cs]
    dblobs = [compress(np.ascontiguousarray(d).tobytes(), codec, level)
              for d in ds]
    return (cblobs + dblobs,
            np.asarray([len(b) for b in cblobs], np.int64),
            np.asarray([len(b) for b in dblobs], np.int64))


def unpack_vdi_segments(blobs: Sequence[bytes], k: int, h: int, w: int,
                        codec: str = "zstd") -> VDI:
    """Inverse of pack_vdi_segments (≅ the decompress-on-receive path,
    handleReceivedBuffersAndUploadForCompositing,
    VDICompositingTest.kt:360-415)."""
    if codec == "zstd" and blobs:
        # sniff the first blob's frame magic so the degrade is SYMMETRIC
        # with pack's: blobs from a zstandard-less writer (zlib) decode
        # on any reader, and genuinely-zstd blobs on a zstandard-less
        # reader get the clear missing-module error instead of a zlib
        # header failure
        if bytes(blobs[0][:4]) == b"\x28\xb5\x2f\xfd":
            if not have_zstd():
                raise ImportError(
                    "these segments were compressed with zstd but the "
                    "zstandard package is not installed")
        else:
            codec = "zlib"
    n = len(blobs) // 2
    seg_w = w // n
    cs = [np.frombuffer(decompress(b, codec), np.float32)
          .reshape(k, 4, h, seg_w) for b in blobs[:n]]
    ds = [np.frombuffer(decompress(b, codec), np.float32)
          .reshape(k, 2, h, seg_w) for b in blobs[n:]]
    return VDI(np.concatenate(cs, axis=-1), np.concatenate(ds, axis=-1))


def dump_path(directory: str, dataset: str, frame: int, kind: str) -> str:
    """Deterministic artifact names (≅ ``${dataset}SubVDI${n}_ndc_col``
    naming, DistributedVolumes.kt:846-851)."""
    os.makedirs(directory, exist_ok=True)
    return os.path.join(directory, f"{dataset}_{kind}_{frame:05d}.npz")
