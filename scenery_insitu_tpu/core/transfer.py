"""Transfer functions and colormaps — gather-free on TPU.

The reference builds per-dataset piecewise-linear opacity ramps and colormaps
(scenery ``TransferFunction.ramp`` + ``Colormap``; reference
DistributedVolumes.kt:179-219, VolumeFromFileExample.kt:405-455) and samples
them through GPU texture hardware. A texture lookup is a *gather*, and the
slice-march hot loop evaluates the transfer function ~26M times per frame —
profiled on a v5e, LUT gathers were 96% of the march cost (584 ms vs 22 ms
without them). TPUs have no texture units, so here a transfer function is
stored directly as its piecewise-linear *knot form* and evaluated as a
relu-sum::

    f(x) = base + sum_i  m_i * relu(x - x_i)

(x_i = knot positions, m_i = slope *changes* at the knots) — a handful of
fully-vectorizable elementwise ops on the VPU, zero gathers, exact for the
polyline the control points define. Knot arrays are padded to a fixed
MAX_KNOTS so every TF shares one pytree structure (one jit cache entry).
Dense LUT views remain available as properties for host-side use
(serialization, plotting).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

LUT_SIZE = 256
MAX_KNOTS = 16


def _relu_terms(xs: np.ndarray, ys: np.ndarray):
    """Knot form (x, slope-deltas, base) of the clamped piecewise-linear
    interpolant through (xs, ys): f equals np.interp(x, xs, ys) on [0, 1]."""
    xs = np.asarray(xs, np.float32)
    ys = np.asarray(ys, np.float32)
    slopes = np.diff(ys) / np.maximum(np.diff(xs), 1e-6)
    s_in = np.concatenate([[0.0], slopes]).astype(np.float32)
    s_out = np.concatenate([slopes, [0.0]]).astype(np.float32)
    deltas = s_out - s_in
    # value at x=0 with all relu terms inactive = left-clamped value
    return xs, deltas, np.float32(ys[0])


def _pad(x: np.ndarray, fill: float) -> np.ndarray:
    out = np.full(MAX_KNOTS, fill, np.float32)
    out[: len(x)] = x
    return out


def _pad2(x: np.ndarray) -> np.ndarray:
    out = np.zeros((MAX_KNOTS, x.shape[1]), np.float32)
    out[: len(x)] = x
    return out


class TransferFunction(NamedTuple):
    """Maps normalized scalar value [0,1] -> (rgb, alpha). Knot form; see
    module docstring. Inactive (padding) knots sit at x=2 with zero slope."""

    alpha_x: jnp.ndarray   # f32[MAX_KNOTS] alpha knot positions
    alpha_m: jnp.ndarray   # f32[MAX_KNOTS] alpha slope deltas
    alpha_b: jnp.ndarray   # f32[]          alpha at x=0
    color_x: jnp.ndarray   # f32[MAX_KNOTS] color knot positions
    color_m: jnp.ndarray   # f32[MAX_KNOTS, 3] per-channel slope deltas
    color_b: jnp.ndarray   # f32[3]         rgb at x=0

    @classmethod
    def from_polylines(cls, alpha_pts: Sequence[Tuple[float, float]],
                       color_xs: np.ndarray, color_rgb: np.ndarray
                       ) -> "TransferFunction":
        alpha_pts = sorted(alpha_pts)
        if len(alpha_pts) > MAX_KNOTS - 1:
            raise ValueError(f"at most {MAX_KNOTS - 1} alpha control points")
        ax, am, ab = _relu_terms(np.array([p[0] for p in alpha_pts]),
                                 np.array([p[1] for p in alpha_pts]))
        cx, _, _ = _relu_terms(color_xs, color_rgb[:, 0])
        cms = np.stack([_relu_terms(color_xs, color_rgb[:, c])[1]
                        for c in range(3)], axis=-1)
        return cls(jnp.asarray(_pad(ax, 2.0)), jnp.asarray(_pad(am, 0.0)),
                   jnp.float32(ab),
                   jnp.asarray(_pad(cx, 2.0)), jnp.asarray(_pad2(cms)),
                   jnp.asarray(color_rgb[0], jnp.float32))

    @classmethod
    def ramp(cls, low: float = 0.0, high: float = 1.0, max_alpha: float = 1.0,
             colormap: str = "grays") -> "TransferFunction":
        """Opacity 0 below `low`, linear to `max_alpha` at `high`
        (≅ scenery TransferFunction.ramp used at DistributedVolumes.kt:183)."""
        high = max(high, low + 1e-6)
        xs, rgb = colormap_polyline(colormap)
        return cls.from_polylines([(low, 0.0), (high, max_alpha)], xs, rgb)

    @classmethod
    def points(cls, pts: Sequence[Tuple[float, float]],
               colormap: str = "grays") -> "TransferFunction":
        """Piecewise-linear opacity through (value, alpha) control points
        (≅ the addControlPoint chains, DistributedVolumes.kt:187-217)."""
        xs, rgb = colormap_polyline(colormap)
        return cls.from_polylines(pts, xs, rgb)

    def __call__(self, value: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Sample -> (rgb f32[..., 3], alpha f32[...]). Gather-free."""
        x = jnp.clip(value, 0.0, 1.0)[..., None]
        a = self.alpha_b + jnp.sum(
            self.alpha_m * jnp.maximum(x - self.alpha_x, 0.0), axis=-1)
        tc = jnp.maximum(x - self.color_x, 0.0)           # [..., K]
        rgb = self.color_b + jnp.tensordot(tc, self.color_m, axes=([-1], [0]))
        return rgb, a

    # ------------------------------------------------ dense LUT views (host)
    @property
    def alpha_lut(self) -> jnp.ndarray:
        """f32[LUT_SIZE] dense sampling (serialization / plotting)."""
        return self(jnp.linspace(0.0, 1.0, LUT_SIZE))[1]

    @property
    def color_lut(self) -> jnp.ndarray:
        """f32[LUT_SIZE, 3] dense sampling."""
        return self(jnp.linspace(0.0, 1.0, LUT_SIZE))[0]

    def max_alpha_in(self, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
        """Max alpha over value interval(s) [lo, hi] (same leading shape) —
        the conservative bound the occupancy/empty-space-skip machinery needs
        (a slab whose value range maps to zero alpha everywhere can be
        skipped even under interpolation, because interpolated values stay
        inside the slab's [min, max])."""
        lo = jnp.clip(lo, 0.0, 1.0)[..., None]
        hi = jnp.clip(hi, 0.0, 1.0)[..., None]
        ends = jnp.concatenate([
            self.alpha_b + jnp.sum(
                self.alpha_m * jnp.maximum(lo - self.alpha_x, 0.0), -1,
                keepdims=True),
            self.alpha_b + jnp.sum(
                self.alpha_m * jnp.maximum(hi - self.alpha_x, 0.0), -1,
                keepdims=True)], axis=-1)
        # interior maxima can only sit at knots inside (lo, hi);
        # alpha at knot j = base + sum_i m_i * relu(x_j - x_i)
        knot_vals = self.alpha_b + jnp.sum(
            self.alpha_m * jnp.maximum(self.alpha_x[:, None]
                                       - self.alpha_x[None, :], 0.0), -1)
        inside = (self.alpha_x >= lo) & (self.alpha_x <= hi)
        interior = jnp.max(jnp.where(inside, knot_vals, -jnp.inf), axis=-1)
        return jnp.maximum(jnp.max(ends, axis=-1), interior)


def opacity_edges(tf: TransferFunction, eps: float = 1e-4) -> np.ndarray:
    """Sorted f32[M] positions of the TF's ACTIVE opacity knots — where
    the alpha polyline changes slope — host-side (numpy). This is the
    edge set of the LOD planner's TF-straddle coarsening gate
    (`parallel.lod.select_levels`; docs/PERF.md "LOD marching"): pooling
    a brick whose value range crosses one of these positions averages
    across an opacity feature and can erase or invent it, so such bricks
    must stay level 0. Padding knots (x = 2, zero slope) and knots whose
    |slope delta| <= ``eps`` carry no feature and are dropped."""
    x = np.asarray(tf.alpha_x, np.float32)
    m = np.asarray(tf.alpha_m, np.float32)
    keep = (x <= 1.0) & (np.abs(m) > eps)
    return np.sort(x[keep])


def colormap_polyline(name: str) -> Tuple[np.ndarray, np.ndarray]:
    """Built-in colormaps as exact piecewise-linear polylines
    (xs f32[K], rgb f32[K, 3]) (≅ scenery Colormap.get, used with
    "hot"/"jet"/"grays" at VolumeFromFileExample.kt:399-403)."""
    if name == "grays":
        xs = np.array([0.0, 1.0], np.float32)
        rgb = np.array([[0, 0, 0], [1, 1, 1]], np.float32)
    elif name == "hot":
        xs = np.array([0.0, 1 / 3, 2 / 3, 1.0], np.float32)
        rgb = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 0], [1, 1, 1]],
                       np.float32)
    elif name == "jet":
        # every kink of clip(1.5-|4x-c|, 0, 1) for c=3,2,1 lies on the k/8
        # grid, so sampling there reproduces the formula exactly
        xs = np.linspace(0.0, 1.0, 9, dtype=np.float32)
        r = np.clip(1.5 - np.abs(4 * xs - 3), 0, 1)
        g = np.clip(1.5 - np.abs(4 * xs - 2), 0, 1)
        b = np.clip(1.5 - np.abs(4 * xs - 1), 0, 1)
        rgb = np.stack([r, g, b], -1).astype(np.float32)
    elif name == "viridis":
        # 11-anchor approximation of matplotlib viridis
        rgb = np.array([
            [0.267, 0.005, 0.329], [0.283, 0.141, 0.458],
            [0.254, 0.265, 0.530], [0.207, 0.372, 0.553],
            [0.164, 0.471, 0.558], [0.128, 0.567, 0.551],
            [0.135, 0.659, 0.518], [0.267, 0.749, 0.441],
            [0.478, 0.821, 0.318], [0.741, 0.873, 0.150],
            [0.993, 0.906, 0.144]], np.float32)
        xs = np.linspace(0.0, 1.0, len(rgb), dtype=np.float32)
    else:
        raise ValueError(f"unknown colormap {name!r}")
    return xs, rgb


def colormap_lut(name: str, n: int = LUT_SIZE) -> np.ndarray:
    """Dense f32[n, 3] sampling of a built-in colormap (host-side users:
    particle splat color tables, previews)."""
    xs, rgb = colormap_polyline(name)
    x = np.linspace(0.0, 1.0, n, dtype=np.float32)
    return np.stack([np.interp(x, xs, rgb[:, c]) for c in range(3)],
                    -1).astype(np.float32)


# Per-dataset transfer functions mirroring the reference's hand-tuned tables
# (VolumeFromFileExample.kt:405-455, DistributedVolumes.kt:179-219).
DATASET_TRANSFER_FUNCTIONS = {
    "kingsnake": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.43, 0.0), (0.5, 0.005)], "grays"),
    "beechnut": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.43, 0.0), (0.457, 0.321), (0.494, 0.0), (1.0, 0.0)], "grays"),
    "simulation": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.1, 0.0), (0.15, 0.1), (0.22, 0.05), (1.0, 0.1)], "hot"),
    "rayleigh_taylor": lambda: TransferFunction.points(
        [(0.0, 0.3), (0.3, 0.05), (0.5, 0.0), (0.7, 0.05), (1.0, 0.3)], "jet"),
    "rotstrat": lambda: TransferFunction.ramp(0.0, 1.0, 0.4, "jet"),
    "procedural": lambda: TransferFunction.ramp(0.05, 0.8, 0.5, "hot"),
    "gray_scott": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.12, 0.0), (0.3, 0.12), (0.65, 0.3), (1.0, 0.5)], "viridis"),
    # vorticity-magnitude fields (vortex sim + the hybrid tracer mode render
    # the same field, so the session and the single-chip Config 5 pipeline
    # must agree on one TF)
    "vortex": lambda: TransferFunction.ramp(0.0, 1.0, 0.4, "jet"),
    "hybrid": lambda: TransferFunction.ramp(0.0, 1.0, 0.4, "jet"),
    # particle sims render sort-first splats and never consult the TF,
    # but the session still constructs one — registering them keeps a
    # REGISTERED scenario (scenery_insitu_tpu/scenarios) off the
    # unknown-dataset ledger
    "lennard_jones": lambda: TransferFunction.ramp(0.05, 0.8, 0.5, "hot"),
    "sho": lambda: TransferFunction.ramp(0.05, 0.8, 0.5, "hot"),
}


def for_dataset(name: str) -> TransferFunction:
    try:
        return DATASET_TRANSFER_FUNCTIONS[name.lower()]()
    except KeyError:
        # an unknown dataset renders with the generic gray ramp — a real
        # behavior change (a typo'd runtime.dataset silently loses the
        # tuned TF), so it lands on the fallback ledger
        from scenery_insitu_tpu import obs

        obs.degrade("core.dataset_tf", name, "grays_ramp",
                    f"no tuned transfer function for dataset {name!r} "
                    f"(known: {sorted(DATASET_TRANSFER_FUNCTIONS)})")
        return TransferFunction.ramp(0.05, 0.8, 0.5, "grays")
