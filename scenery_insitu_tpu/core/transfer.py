"""Transfer functions and colormaps.

The reference builds per-dataset piecewise-linear opacity ramps and colormaps
(scenery ``TransferFunction.ramp`` + ``Colormap``; reference
DistributedVolumes.kt:179-219, VolumeFromFileExample.kt:405-455). Here a
transfer function is a pair of lookup tables sampled with linear
interpolation — a dense [N] opacity LUT and an [N, 3] color LUT — built from
control points, fully differentiable and jit-friendly.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

LUT_SIZE = 256


class TransferFunction(NamedTuple):
    """Maps normalized scalar value [0,1] -> (rgb, alpha)."""

    color_lut: jnp.ndarray   # f32[N, 3]
    alpha_lut: jnp.ndarray   # f32[N]

    @classmethod
    def ramp(cls, low: float = 0.0, high: float = 1.0, max_alpha: float = 1.0,
             colormap: str = "grays") -> "TransferFunction":
        """Opacity 0 below `low`, linear to `max_alpha` at `high`
        (≅ scenery TransferFunction.ramp used at DistributedVolumes.kt:183)."""
        x = np.linspace(0.0, 1.0, LUT_SIZE, dtype=np.float32)
        a = np.clip((x - low) / max(high - low, 1e-6), 0.0, 1.0) * max_alpha
        return cls(jnp.asarray(colormap_lut(colormap)), jnp.asarray(a))

    @classmethod
    def points(cls, pts: Sequence[Tuple[float, float]],
               colormap: str = "grays") -> "TransferFunction":
        """Piecewise-linear opacity through (value, alpha) control points
        (≅ the addControlPoint chains, DistributedVolumes.kt:187-217)."""
        pts = sorted(pts)
        xs = np.array([p[0] for p in pts], np.float32)
        ys = np.array([p[1] for p in pts], np.float32)
        x = np.linspace(0.0, 1.0, LUT_SIZE, dtype=np.float32)
        a = np.interp(x, xs, ys).astype(np.float32)
        return cls(jnp.asarray(colormap_lut(colormap)), jnp.asarray(a))

    def __call__(self, value: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Sample -> (rgb f32[..., 3], alpha f32[...]). Linear interp."""
        n = self.alpha_lut.shape[0]
        x = jnp.clip(value, 0.0, 1.0) * (n - 1)
        i0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, n - 2)
        frac = x - i0
        a = self.alpha_lut[i0] * (1 - frac) + self.alpha_lut[i0 + 1] * frac
        rgb = (self.color_lut[i0] * (1 - frac)[..., None]
               + self.color_lut[i0 + 1] * frac[..., None])
        return rgb, a


def colormap_lut(name: str, n: int = LUT_SIZE) -> np.ndarray:
    """Built-in colormaps as f32[n, 3] (≅ scenery Colormap.get, used with
    "hot"/"jet"/"grays" at VolumeFromFileExample.kt:399-403)."""
    x = np.linspace(0.0, 1.0, n, dtype=np.float32)
    if name == "grays":
        rgb = np.stack([x, x, x], -1)
    elif name == "hot":
        r = np.clip(3 * x, 0, 1)
        g = np.clip(3 * x - 1, 0, 1)
        b = np.clip(3 * x - 2, 0, 1)
        rgb = np.stack([r, g, b], -1)
    elif name == "jet":
        r = np.clip(1.5 - np.abs(4 * x - 3), 0, 1)
        g = np.clip(1.5 - np.abs(4 * x - 2), 0, 1)
        b = np.clip(1.5 - np.abs(4 * x - 1), 0, 1)
        rgb = np.stack([r, g, b], -1)
    elif name == "viridis":
        # 8-anchor approximation of matplotlib viridis
        anchors = np.array([
            [0.267, 0.005, 0.329], [0.283, 0.141, 0.458],
            [0.254, 0.265, 0.530], [0.207, 0.372, 0.553],
            [0.164, 0.471, 0.558], [0.128, 0.567, 0.551],
            [0.135, 0.659, 0.518], [0.267, 0.749, 0.441],
            [0.478, 0.821, 0.318], [0.741, 0.873, 0.150],
            [0.993, 0.906, 0.144]], np.float32)
        ax = np.linspace(0, 1, len(anchors))
        rgb = np.stack([np.interp(x, ax, anchors[:, c]) for c in range(3)], -1)
    else:
        raise ValueError(f"unknown colormap {name!r}")
    return rgb.astype(np.float32)


# Per-dataset transfer functions mirroring the reference's hand-tuned tables
# (VolumeFromFileExample.kt:405-455, DistributedVolumes.kt:179-219).
DATASET_TRANSFER_FUNCTIONS = {
    "kingsnake": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.43, 0.0), (0.5, 0.005)], "grays"),
    "beechnut": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.43, 0.0), (0.457, 0.321), (0.494, 0.0), (1.0, 0.0)], "grays"),
    "simulation": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.1, 0.0), (0.15, 0.1), (0.22, 0.05), (1.0, 0.1)], "hot"),
    "rayleigh_taylor": lambda: TransferFunction.points(
        [(0.0, 0.3), (0.3, 0.05), (0.5, 0.0), (0.7, 0.05), (1.0, 0.3)], "jet"),
    "rotstrat": lambda: TransferFunction.ramp(0.0, 1.0, 0.4, "jet"),
    "procedural": lambda: TransferFunction.ramp(0.05, 0.8, 0.5, "hot"),
    "gray_scott": lambda: TransferFunction.points(
        [(0.0, 0.0), (0.12, 0.0), (0.3, 0.12), (0.65, 0.3), (1.0, 0.5)], "viridis"),
}


def for_dataset(name: str) -> TransferFunction:
    try:
        return DATASET_TRANSFER_FUNCTIONS[name.lower()]()
    except KeyError:
        return TransferFunction.ramp(0.05, 0.8, 0.5, "grays")
