from scenery_insitu_tpu.core.camera import Camera  # noqa: F401
from scenery_insitu_tpu.core.volume import Volume  # noqa: F401
from scenery_insitu_tpu.core.transfer import TransferFunction  # noqa: F401
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata  # noqa: F401
