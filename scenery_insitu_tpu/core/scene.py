"""Multi-grid scene management (layer L2).

The reference tracks, per compute partner, a *list* of grids with per-grid
origins, grid extents and domain extents — OpenFPM's domain decomposition
hands each rank an arbitrary set of boxes, not one even slab
(``updateData(partnerNo, numGrids, grids, origins, gridDims, domainDims)``,
reference DistributedVolumeRenderer.kt:57-64,116-160; per-grid Volume nodes
at :341-386). ``MultiGridScene`` is that bookkeeping, TPU-first: each grid
is a `Volume` (static shape ⇒ one jit specialization per grid-set
signature), rendering treats the grids exactly like sort-last ranks — every
grid raycasts/marches against the GLOBAL bounding box and the per-grid
sub-VDIs merge through the ordinary composite kernel. Uneven and
non-power-of-two decompositions need no special casing: disjoint interior
AABBs are the only requirement, the same invariant the reference relies on.

Ghost (halo) layers: simulation grids usually arrive with ghost cells on
some faces (OpenFPM ships them; they make interpolation seam-exact). Pass
``ghost_lo``/``ghost_hi`` voxel counts per axis; samples are clipped to the
interior half-open box so every world position is owned by exactly one
grid, and along the march axis ghost slices are dropped statically so no
slab is double-counted.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import CompositeConfig, RenderConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.core.volume import Volume


class SceneGrid(NamedTuple):
    volume: Volume                     # full data INCLUDING ghost layers
    ghost_lo: Tuple[int, int, int]     # ghost voxels on the min faces (x,y,z)
    ghost_hi: Tuple[int, int, int]     # ghost voxels on the max faces (x,y,z)

    @property
    def interior_min(self) -> jnp.ndarray:
        g = jnp.asarray(self.ghost_lo, jnp.float32)
        return self.volume.origin + g * self.volume.spacing

    @property
    def interior_max(self) -> jnp.ndarray:
        g = jnp.asarray(self.ghost_hi, jnp.float32)
        return self.volume.world_max - g * self.volume.spacing


class MultiGridScene:
    """Per-partner multi-grid bookkeeping + whole-scene rendering."""

    def __init__(self):
        self._grids: Dict[Tuple[int, int], SceneGrid] = {}

    # ------------------------------------------------------------ operator
    def update_data(self, partner: int, grids: Sequence[jnp.ndarray],
                    origins: Sequence, spacing,
                    ghost_lo: Optional[Sequence[Tuple[int, int, int]]] = None,
                    ghost_hi: Optional[Sequence[Tuple[int, int, int]]] = None
                    ) -> None:
        """Replace partner's grid set (≅ updateData,
        DistributedVolumeRenderer.kt:136-160). ``grids[i]`` is f32[D,H,W]
        including ghosts; ``origins[i]`` is the world position of the full
        grid's min corner (x, y, z)."""
        for key in [k for k in self._grids if k[0] == partner]:
            del self._grids[key]
        for i, g in enumerate(grids):
            self.set_grid(partner, i, g, origins[i], spacing,
                          ghost_lo[i] if ghost_lo else (0, 0, 0),
                          ghost_hi[i] if ghost_hi else (0, 0, 0))

    def set_grid(self, partner: int, gid: int, data, origin, spacing,
                 ghost_lo=(0, 0, 0), ghost_hi=(0, 0, 0)) -> None:
        vol = Volume.create(data, origin, spacing)
        self._grids[(partner, gid)] = SceneGrid(vol, tuple(ghost_lo),
                                                tuple(ghost_hi))

    def update_grid(self, partner: int, gid: int, data) -> None:
        """New timestep for an existing grid (≅ updateVolume,
        DistributedVolumes.kt:243-250). Data only — the shape must match
        the registered grid (callers cache extent-derived state on that
        invariant); a repartition/refinement goes through `update_data`."""
        g = self._grids[(partner, gid)]
        data = jnp.asarray(data, jnp.float32)
        if tuple(data.shape) != tuple(g.volume.data.shape):
            raise ValueError(
                f"update_grid({partner}, {gid}): shape {tuple(data.shape)} "
                f"!= registered {tuple(g.volume.data.shape)} — layout "
                "changes must go through update_data")
        self._grids[(partner, gid)] = g._replace(
            volume=g.volume._replace(data=data))

    @property
    def grids(self) -> List[SceneGrid]:
        return [self._grids[k] for k in sorted(self._grids)]

    @property
    def num_grids(self) -> int:
        return len(self._grids)

    def global_bounds(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Union AABB of the grid interiors (the scene's world box)."""
        gs = self.grids
        lo = gs[0].interior_min
        hi = gs[0].interior_max
        for g in gs[1:]:
            lo = jnp.minimum(lo, g.interior_min)
            hi = jnp.maximum(hi, g.interior_max)
        return lo, hi

    # ----------------------------------------------------------- rendering
    def generate_vdi(self, tf: TransferFunction, cam: Camera,
                     width: int, height: int,
                     cfg: Optional[VDIConfig] = None,
                     comp_cfg: Optional[CompositeConfig] = None,
                     max_steps: int = 256) -> Tuple[VDI, VDIMetadata]:
        """Whole-scene VDI on the gather path: each grid raycasts clipped to
        its interior box, the sub-VDIs sort-last composite (grids play the
        role of ranks)."""
        from scenery_insitu_tpu.ops.composite import composite_vdis
        from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

        vdis = []
        meta = None
        for g in self.grids:
            vdi, meta = generate_vdi(g.volume, tf, cam, width, height, cfg,
                                     max_steps=max_steps,
                                     clip_min=g.interior_min,
                                     clip_max=g.interior_max)
            vdis.append(vdi)
        lo, hi = self.global_bounds()
        dims = (hi - lo) / self.grids[0].volume.spacing
        meta = meta._replace(volume_dims=dims)
        out = composite_vdis(jnp.stack([v.color for v in vdis]),
                             jnp.stack([v.depth for v in vdis]), comp_cfg)
        return out, meta

    def generate_vdi_mxu(self, tf: TransferFunction, cam: Camera, spec,
                         cfg: Optional[VDIConfig] = None,
                         comp_cfg: Optional[CompositeConfig] = None
                         ) -> Tuple[VDI, VDIMetadata]:
        """Whole-scene VDI on the MXU slice march. Every grid marches
        against the global box (shared slice ladder + intermediate grid);
        ghost slices along the march axis are dropped statically so no slab
        is double-counted, in-plane ghosts stay for seam-exact bilinear
        with half-open ownership bounds (the same scheme as the distributed
        pipeline's `_mxu_rank_generate`)."""
        from scenery_insitu_tpu.ops import slicer
        from scenery_insitu_tpu.ops.composite import composite_vdis

        lo, hi = self.global_bounds()
        vdis = []
        meta = None
        for vol, ub, vb in self._march_grids(spec, lo, hi):
            vdi, meta, _ = slicer.generate_vdi_mxu(
                vol, tf, cam, spec, cfg, box_min=lo, box_max=hi,
                u_bounds=ub, v_bounds=vb)
            vdis.append(vdi)
        meta = self._scene_meta(meta, lo, hi)
        out = composite_vdis(jnp.stack([v.color for v in vdis]),
                             jnp.stack([v.depth for v in vdis]), comp_cfg)
        return out, meta

    def _march_grids(self, spec, lo, hi):
        """Per-grid (volume, u_bounds, v_bounds) for a whole-scene slice
        march: ghost slices along the march axis dropped statically so no
        slab is double-counted; in-plane ghosts stay for seam-exact
        bilinear with half-open ownership bounds (the same scheme as the
        distributed pipeline's `_mxu_rank_generate`)."""
        a, ua, va = spec.axis, spec.u_axis, spec.v_axis
        data_dim = {0: 2, 1: 1, 2: 0}   # xyz axis -> data dim of [z, y, x]
        out = []
        for g in self.grids:
            dd = data_dim[a]
            n_a = g.volume.data.shape[dd]
            sl = [slice(None)] * 3
            sl[dd] = slice(g.ghost_lo[a], n_a - g.ghost_hi[a] or None)
            data = g.volume.data[tuple(sl)]
            origin = g.volume.origin
            origin = origin.at[a].add(g.ghost_lo[a] * g.volume.spacing[a])
            vol = Volume(data, origin, g.volume.spacing)

            # half-open ownership on the in-plane axes; at the global max
            # face re-admit pos == hi (capped by the volume-extent mask)
            def bounds(ax, g=g):
                blo = g.interior_min[ax]
                bhi = g.interior_max[ax]
                slack = jnp.where(bhi >= hi[ax] - 1e-6,
                                  g.volume.spacing[ax], 0.0)
                return (blo, bhi + slack)

            out.append((vol, bounds(ua), bounds(va)))
        return out

    def _scene_meta(self, meta, lo, hi):
        dims = (hi - lo) / self.grids[0].volume.spacing
        return meta._replace(volume_dims=dims)

    def initial_thresholds(self, tf: TransferFunction, cam: Camera, spec,
                           cfg: Optional[VDIConfig] = None):
        """Temporal-threshold seed with [G, nj, ni] maps, one per grid
        (each grid's sub-VDI runs its own supersegment machine —
        counterpart of slicer.initial_threshold for the whole scene)."""
        from scenery_insitu_tpu.ops import slicer

        lo, hi = self.global_bounds()
        states = [slicer.initial_threshold(vol, tf, cam, spec, cfg,
                                           box_min=lo, box_max=hi,
                                           u_bounds=ub, v_bounds=vb)
                  for vol, ub, vb in self._march_grids(spec, lo, hi)]
        return jax.tree_util.tree_map(lambda *a: jnp.stack(a), *states)

    def generate_vdi_mxu_temporal(self, tf: TransferFunction, cam: Camera,
                                  spec, thresholds,
                                  cfg: Optional[VDIConfig] = None,
                                  comp_cfg: Optional[CompositeConfig] = None
                                  ) -> Tuple[VDI, VDIMetadata, object]:
        """Whole-scene VDI with carried per-grid threshold state (one
        march per grid per frame; see slicer.generate_vdi_mxu_temporal).
        Returns (vdi, meta, next_thresholds)."""
        from scenery_insitu_tpu.ops import slicer
        from scenery_insitu_tpu.ops.composite import composite_vdis

        lo, hi = self.global_bounds()
        vdis, thrs = [], []
        meta = None
        for i, (vol, ub, vb) in enumerate(self._march_grids(spec, lo, hi)):
            state_i = jax.tree_util.tree_map(lambda x: x[i], thresholds)
            vdi, meta, _, thr = slicer.generate_vdi_mxu_temporal(
                vol, tf, cam, spec, state_i, cfg, box_min=lo,
                box_max=hi, u_bounds=ub, v_bounds=vb)
            vdis.append(vdi)
            thrs.append(thr)
        meta = self._scene_meta(meta, lo, hi)
        out = composite_vdis(jnp.stack([v.color for v in vdis]),
                             jnp.stack([v.depth for v in vdis]), comp_cfg)
        return (out, meta,
                jax.tree_util.tree_map(lambda *a: jnp.stack(a), *thrs))

    def render(self, tf: TransferFunction, cam: Camera,
               width: int, height: int,
               cfg: Optional[RenderConfig] = None) -> jnp.ndarray:
        """Whole-scene plain image: per-grid raycast + sort-last plain
        composite (≅ the reference's per-grid Volume nodes all rendered
        into one view)."""
        import dataclasses

        from scenery_insitu_tpu.ops.composite import composite_plain
        from scenery_insitu_tpu.ops.raycast import raycast

        cfg = cfg or RenderConfig(width=width, height=height)
        # background blended once at the composite; AO off per grid — a
        # per-grid occlusion blur edge-clamps at grid boundaries instead
        # of seeing neighbor grids (single-volume feature, ops/ao.py)
        rank_cfg = dataclasses.replace(cfg, background=(0.0,) * 4,
                                       ao_strength=0.0)
        outs = [raycast(g.volume, tf, cam, width, height, rank_cfg,
                        clip_min=g.interior_min, clip_max=g.interior_max)
                for g in self.grids]
        return composite_plain(jnp.stack([o.image for o in outs]),
                               jnp.stack([o.depth for o in outs]),
                               cfg.background)
