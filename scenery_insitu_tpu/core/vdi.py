"""Volumetric Depth Image (VDI) data model.

A VDI stores, per pixel, an ordered list of at most K "supersegments": depth-
bounded slabs of premultiplied RGBA that summarize the volume along that
pixel's ray. This mirrors the reference's OutputSubVDIColor rgba32f
``[K, H, W]`` + OutputSubVDIDepth r32f ``[2K, H, W]`` textures (reference
DistributedVolumes.kt:331-368) with one layout decision made for TPU: (H, W)
are always the trailing (sublane, lane) dims and K/channel axes lead.

Empty-slot convention (static K keeps every shape jit-compatible):
``alpha == 0`` and ``depth == +inf`` for unused slots; live slots are sorted
front-to-back and non-overlapping per pixel.

Depths are the world-space ray parameter t of the generating camera — see the
package docstring for why (one depth encoding instead of the reference's
three).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np


class VDIMetadata(NamedTuple):
    """Everything needed to interpret / re-render a VDI
    (≅ scenery VDIData: projection, view, volumeDims, model, nw, windowDims —
    reference DistributedVolumes.kt:706-716)."""

    projection: jnp.ndarray    # f32[4, 4]
    view: jnp.ndarray          # f32[4, 4]
    model: jnp.ndarray         # f32[4, 4] volume model matrix (origin/spacing)
    volume_dims: jnp.ndarray   # f32[3] (x, y, z) voxel counts
    window_dims: jnp.ndarray   # i32[2] (width, height)
    nw: jnp.ndarray            # f32[] world-space step size ("nw" in reference)
    index: jnp.ndarray         # i32[] frame index
    # i32[] payload precision code (ops.wire.WIRE_CODES: 0 = f32, the
    # in-memory convention; 1 = qpack8, set by the host-side quantize
    # pass of io.vdi_io / runtime.streaming so decoders know to
    # dequantize). Readers (load_vdi / VDISubscriber) decode buffers back
    # to f32 and keep the tag as provenance; writers always re-stamp it
    # to match what they actually write, so an artifact/frame never
    # mislabels its own buffers. Trailing with a default so 7-field
    # constructions and pre-tag artifacts keep working.
    precision: jnp.ndarray = np.int32(0)

    @classmethod
    def create(cls, projection, view, model=None, volume_dims=(0, 0, 0),
               window_dims=(0, 0), nw: float = 0.0, index: int = 0,
               precision: int = 0) -> "VDIMetadata":
        model = jnp.eye(4, dtype=jnp.float32) if model is None else jnp.asarray(model, jnp.float32)
        return cls(jnp.asarray(projection, jnp.float32),
                   jnp.asarray(view, jnp.float32), model,
                   jnp.asarray(volume_dims, jnp.float32),
                   jnp.asarray(window_dims, jnp.int32),
                   jnp.asarray(nw, jnp.float32),
                   jnp.asarray(index, jnp.int32),
                   jnp.asarray(precision, jnp.int32))


class VDI(NamedTuple):
    color: jnp.ndarray   # f32[K, 4, H, W] premultiplied RGBA per supersegment
    depth: jnp.ndarray   # f32[K, 2, H, W] (t_start, t_end); +inf when empty

    @property
    def k(self) -> int:
        return self.color.shape[0]

    @property
    def height(self) -> int:
        return self.color.shape[2]

    @property
    def width(self) -> int:
        return self.color.shape[3]

    @property
    def count(self) -> jnp.ndarray:
        """i32[H, W] number of live supersegments per pixel."""
        return jnp.sum(self.color[:, 3] > 0.0, axis=0).astype(jnp.int32)

    @classmethod
    def empty(cls, k: int, height: int, width: int) -> "VDI":
        return cls(jnp.zeros((k, 4, height, width), jnp.float32),
                   jnp.full((k, 2, height, width), jnp.inf, jnp.float32))


def render_vdi_same_view(vdi: VDI, background: Tuple[float, ...] = (0, 0, 0, 0)
                         ) -> jnp.ndarray:
    """Alpha-under all supersegments front-to-back from the generating
    camera's own view — the cheapest full decode of a VDI, used for parity
    tests (≅ SimpleVDIRenderer.comp:43-74). Returns f32[4, H, W]."""
    import jax

    order = jnp.argsort(vdi.depth[:, 0], axis=0)                    # [K, H, W]
    color = jnp.take_along_axis(vdi.color, order[:, None], axis=0)  # [K,4,H,W]

    def body(acc, src):
        return acc + (1.0 - acc[3:4]) * src, None

    acc, _ = jax.lax.scan(body, jnp.zeros_like(color[0]), color)
    bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
    return acc + (1.0 - acc[3:4]) * bg


def vdi_nbytes(k: int, height: int, width: int) -> int:
    """Uncompressed payload size (color + depth) in bytes; the reference's
    per-rank per-frame wire size (SURVEY.md §6: ~442 MB at 1280x720, K=20)."""
    return k * height * width * (4 + 2) * 4


def to_numpy(vdi: VDI) -> Tuple[np.ndarray, np.ndarray]:
    return np.asarray(vdi.color), np.asarray(vdi.depth)
