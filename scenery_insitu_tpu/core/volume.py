"""Volume data model.

A Volume is a scalar field ``f32[D, H, W]`` (indexed ``[z, y, x]``) with a
world-space placement: ``origin`` (world position of the grid's min corner)
and per-axis ``spacing`` (world size of one voxel). This replaces the
reference's scenery ``Volume.fromBuffer`` nodes positioned at per-grid origins
(reference DistributedVolumes.kt:147-240; DistributedVolumeRenderer.kt:326-394)
and its raw-file loader ``fromPathRaw`` (VolumeFromFileExample.kt:159-217).

Values are kept normalized to [0, 1]; loaders divide by the dtype range
(uint8/uint16 raw files, is16bit flag ≅ DistributedVolumes.kt:147).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np


class Volume(NamedTuple):
    # f32[D, H, W] normalized scalar field, vol[z, y, x] — or, for
    # pre-shaded content (the novel-view proxy), f32[ch, D, H, W] with a
    # leading channel dim (premultiplied RGBA; rendered without a TF)
    data: jnp.ndarray
    origin: jnp.ndarray    # f32[3] world position of min corner (x, y, z)
    spacing: jnp.ndarray   # f32[3] world size of a voxel (x, y, z)

    @staticmethod
    def _field_dtype(data):
        """Everything normalizes to f32 EXCEPT bf16, which is preserved:
        a bf16 field is the deliberate memory plan of very large volumes
        (the 1024^3 march's permuted copy halves; the resampling einsum
        casts to bf16 anyway — see models/pipelines.py render_dtype)."""
        if getattr(data, "dtype", None) == jnp.bfloat16:
            return jnp.bfloat16
        return jnp.float32

    @classmethod
    def create(cls, data, origin=(0.0, 0.0, 0.0), spacing=(1.0, 1.0, 1.0)) -> "Volume":
        return cls(jnp.asarray(data, cls._field_dtype(data)),
                   jnp.asarray(origin, jnp.float32),
                   jnp.asarray(spacing, jnp.float32))

    @classmethod
    def centered(cls, data, extent: float = 2.0) -> "Volume":
        """Place the volume centered at the world origin with its largest side
        spanning `extent` world units."""
        data = jnp.asarray(data, cls._field_dtype(data))
        d, h, w = data.shape
        vox = extent / max(d, h, w)
        size = jnp.array([w * vox, h * vox, d * vox], jnp.float32)
        return cls(data, -size / 2.0, jnp.full((3,), vox, jnp.float32))

    @property
    def dims_xyz(self) -> Tuple[int, int, int]:
        d, h, w = self.data.shape[-3:]
        return (w, h, d)

    @property
    def world_min(self) -> jnp.ndarray:
        return self.origin

    @property
    def world_max(self) -> jnp.ndarray:
        d, h, w = self.data.shape[-3:]
        return self.origin + jnp.array([w, h, d], jnp.float32) * self.spacing

    def world_to_voxel(self, p: jnp.ndarray) -> jnp.ndarray:
        """World position [..., 3] (x,y,z) -> continuous voxel coords [..., 3]
        (x,y,z), where voxel centers sit at integer+0.5."""
        return (p - self.origin) / self.spacing


def load_raw(path: str, dims_xyz: Tuple[int, int, int],
             is16bit: bool = False, extent: float = 2.0) -> Volume:
    """Load a raw binary volume file (x-fastest layout, as the reference's
    dataset table expects: VolumeFromFileExample.kt:104-120, 159-217)."""
    w, h, d = dims_xyz
    dtype = np.uint16 if is16bit else np.uint8
    raw = np.fromfile(path, dtype=dtype, count=w * h * d).reshape(d, h, w)
    data = raw.astype(np.float32) / float(np.iinfo(dtype).max)
    return Volume.centered(jnp.asarray(data), extent)


# Dataset dimension table mirroring VolumeFromFileExample.kt:104-120 so raw
# files drop in by name.
DATASET_DIMS_XYZ = {
    "kingsnake": (1024, 1024, 795),
    "beechnut": (1024, 1024, 1546),
    "simulation": (2048, 2048, 1920),
    "rayleigh_taylor": (1024, 1024, 1024),
    "microscopy": (1024, 1024, 1040),
    "rotstrat": (4096, 4096, 4096),
}


def load_dataset(name: str, data_dir: str, extent: float = 2.0) -> Volume:
    dims = DATASET_DIMS_XYZ[name.lower()]
    path = os.path.join(data_dir, f"{name}.raw")
    return load_raw(path, dims, is16bit=True, extent=extent)


def procedural_volume(size: int = 128, seed: int = 0,
                      kind: str = "blobs") -> Volume:
    """Procedural test volume (≅ Volume.generateProceduralVolume used as the
    fake-simulation fixture, reference VDIGenerationExample.kt:182-213)."""
    rng = np.random.default_rng(seed)
    z, y, x = np.meshgrid(*(np.linspace(-1, 1, size, dtype=np.float32),) * 3,
                          indexing="ij")
    if kind == "blobs":
        field = np.zeros_like(x)
        for _ in range(6):
            c = rng.uniform(-0.6, 0.6, 3).astype(np.float32)
            r = rng.uniform(0.15, 0.4)
            field += np.exp(-(((x - c[0]) ** 2 + (y - c[1]) ** 2
                               + (z - c[2]) ** 2) / (r * r)))
        field /= field.max()
    elif kind == "shell":
        r = np.sqrt(x * x + y * y + z * z)
        field = np.exp(-((r - 0.6) ** 2) / 0.01).astype(np.float32)
    elif kind == "gradient":
        field = (x + 1) / 2
    else:
        raise ValueError(f"unknown procedural volume kind {kind!r}")
    return Volume.centered(jnp.asarray(field.astype(np.float32)))
