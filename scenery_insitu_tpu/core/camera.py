"""Camera model and ray generation.

One convention, chosen once: right-handed OpenGL camera (looks down -z in eye
space), NDC z in [-1, 1], image row 0 at the *top* of the screen. The
reference needed a "Vulkan projection fix" matrix and a y-flip scattered
through shaders (reference DistributedVolumes.kt:67-79, ConvertToNDC.comp:238);
here rays are generated directly from the inverse view-projection, exactly as
VDIGenerator.comp:289 does with ``ipv = InverseView * InverseProjection``.

Supersegment/fragment depths throughout the framework are the world-space ray
parameter ``t`` (unit-length directions), NOT NDC z — see package docstring.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp


class Camera(NamedTuple):
    """Pinhole camera. All leaves are jnp arrays so Camera is a jit-friendly
    pytree (≅ the camera pose + projection the reference passes in VDIData:
    DistributedVolumes.kt:706-716)."""

    eye: jnp.ndarray        # f32[3] world-space position
    target: jnp.ndarray     # f32[3] look-at point
    up: jnp.ndarray         # f32[3]
    fov_y: jnp.ndarray      # f32[] vertical field of view, radians
    near: jnp.ndarray       # f32[]
    far: jnp.ndarray        # f32[]

    @classmethod
    def create(cls, eye, target=(0.0, 0.0, 0.0), up=(0.0, 1.0, 0.0),
               fov_y_deg: float = 50.0, near: float = 0.1, far: float = 1000.0
               ) -> "Camera":
        f32 = lambda v: jnp.asarray(v, jnp.float32)
        return cls(f32(eye), f32(target), f32(up),
                   f32(jnp.deg2rad(fov_y_deg)), f32(near), f32(far))


def look_at(eye: jnp.ndarray, target: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    """World -> eye 4x4 view matrix (OpenGL convention)."""
    fwd = _normalize(target - eye)
    right = _normalize(jnp.cross(fwd, up))
    true_up = jnp.cross(right, fwd)
    rot = jnp.stack([right, true_up, -fwd])           # rows
    trans = -rot @ eye
    view = jnp.eye(4, dtype=jnp.float32)
    view = view.at[:3, :3].set(rot)
    view = view.at[:3, 3].set(trans)
    return view


def perspective(fov_y: jnp.ndarray, aspect: float, near, far) -> jnp.ndarray:
    """OpenGL perspective projection, NDC z in [-1, 1]."""
    f = 1.0 / jnp.tan(fov_y / 2.0)
    near = jnp.asarray(near, jnp.float32)
    far = jnp.asarray(far, jnp.float32)
    proj = jnp.zeros((4, 4), jnp.float32)
    proj = proj.at[0, 0].set(f / aspect)
    proj = proj.at[1, 1].set(f)
    proj = proj.at[2, 2].set((far + near) / (near - far))
    proj = proj.at[2, 3].set(2.0 * far * near / (near - far))
    proj = proj.at[3, 2].set(-1.0)
    return proj


def frustum(l, r, b, t, n, f) -> jnp.ndarray:
    """Off-axis (glFrustum-style) OpenGL perspective projection from the
    near-plane window [l, r] x [b, t]; NDC z in [-1, 1]. All arguments may
    be traced scalars (the slice-march virtual camera rebuilds its frustum
    every frame, ops/slicer.py)."""
    l, r, b, t, n, f = (jnp.asarray(v, jnp.float32) for v in (l, r, b, t, n, f))
    zero = jnp.zeros_like(n)
    row0 = jnp.stack([2 * n / (r - l), zero, (r + l) / (r - l), zero])
    row1 = jnp.stack([zero, 2 * n / (t - b), (t + b) / (t - b), zero])
    row2 = jnp.stack([zero, zero, (f + n) / (n - f), 2 * f * n / (n - f)])
    row3 = jnp.stack([zero, zero, -jnp.ones_like(n), zero])
    return jnp.stack([row0, row1, row2, row3])


def view_matrix(cam: Camera) -> jnp.ndarray:
    return look_at(cam.eye, cam.target, cam.up)


def projection_matrix(cam: Camera, width: int, height: int) -> jnp.ndarray:
    return perspective(cam.fov_y, width / height, cam.near, cam.far)


def pixel_rays(cam: Camera, width: int, height: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel world-space rays.

    Returns (origin f32[3], dirs f32[3, H, W]); dirs are unit length so the
    ray parameter t is world distance. Pixel centers; row 0 = top.
    ≅ VDIGenerator.comp:283-296 (ipv unproject of the NDC pixel).
    """
    view = view_matrix(cam)
    proj = projection_matrix(cam, width, height)
    inv_vp = jnp.linalg.inv(proj @ view)

    j = (jnp.arange(width, dtype=jnp.float32) + 0.5) / width * 2.0 - 1.0
    i = 1.0 - (jnp.arange(height, dtype=jnp.float32) + 0.5) / height * 2.0
    ndc_x, ndc_y = jnp.meshgrid(j, i, indexing="xy")      # [H, W]

    def unproject(z):
        ndc = jnp.stack([ndc_x, ndc_y,
                         jnp.full_like(ndc_x, z), jnp.ones_like(ndc_x)])  # [4,H,W]
        w = jnp.einsum("ab,bhw->ahw", inv_vp, ndc)
        return w[:3] / w[3:4]

    # Direction through the exactly-known eye and the near-plane point: the
    # f32 unprojection of the far plane (ndc z=+1) is badly conditioned
    # (division by w ~ 0), so near-minus-far directions drift ~1e-3.
    p_near = unproject(-1.0)
    dirs = _normalize(p_near - cam.eye.reshape(3, 1, 1), axis=0)
    return cam.eye, dirs


def world_to_ndc(point_w: jnp.ndarray, view: jnp.ndarray, proj: jnp.ndarray) -> jnp.ndarray:
    """Project world points [..., 3] to NDC [..., 3] (for parity checks and
    the novel-view VDI renderer)."""
    p = jnp.concatenate([point_w, jnp.ones_like(point_w[..., :1])], axis=-1)
    clip = p @ (proj @ view).T
    return clip[..., :3] / clip[..., 3:4]


def orbit(cam: Camera, yaw: jnp.ndarray, pitch: jnp.ndarray = 0.0) -> Camera:
    """Rotate the eye around the target (≅ rotateCamera benchmark sweep,
    reference DistributedVolumes.kt:527-623)."""
    rel = cam.eye - cam.target
    cy, sy = jnp.cos(yaw), jnp.sin(yaw)
    rel = jnp.stack([cy * rel[0] + sy * rel[2], rel[1],
                     -sy * rel[0] + cy * rel[2]])
    cp, sp = jnp.cos(pitch), jnp.sin(pitch)
    rel = jnp.stack([rel[0], cp * rel[1] - sp * rel[2],
                     sp * rel[1] + cp * rel[2]])
    return cam._replace(eye=cam.target + rel)


def _normalize(v: jnp.ndarray, axis: int = -1, eps: float = 1e-12) -> jnp.ndarray:
    return v / jnp.maximum(jnp.linalg.norm(v, axis=axis, keepdims=True), eps)
