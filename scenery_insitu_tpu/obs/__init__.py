"""Observability layer: structured spans, device counters, the fallback
ledger, Perfetto/JSONL exporters, the fleet telemetry side-channel
(``obs.collector``) and the live SLO engine (``obs.slo``)
(docs/OBSERVABILITY.md).

Import surface is intentionally tiny and JAX-free so hot modules
(ops/*, io/*) can ``from scenery_insitu_tpu import obs`` at module load
without cost or cycles; ``obs.device`` (the cost-analysis snapshot)
touches JAX only inside its functions, and ``obs.collector`` touches
zmq only inside its classes.
"""

from scenery_insitu_tpu.obs.recorder import (Recorder, clear_ledger,
                                             counter_registry, degrade,
                                             flight_flush, get_recorder,
                                             ledger, ledger_registry,
                                             set_recorder)

__all__ = ["Recorder", "degrade", "ledger", "ledger_registry",
           "counter_registry", "clear_ledger", "flight_flush",
           "get_recorder", "set_recorder"]
