"""Observability layer: structured spans, device counters, the fallback
ledger and Perfetto/JSONL exporters (docs/OBSERVABILITY.md).

Import surface is intentionally tiny and JAX-free so hot modules
(ops/*, io/*) can ``from scenery_insitu_tpu import obs`` at module load
without cost or cycles; ``obs.device`` (the cost-analysis snapshot)
touches JAX only inside its functions.
"""

from scenery_insitu_tpu.obs.recorder import (Recorder, clear_ledger,
                                             degrade, get_recorder,
                                             ledger, ledger_registry,
                                             set_recorder)

__all__ = ["Recorder", "degrade", "ledger", "ledger_registry",
           "clear_ledger", "get_recorder", "set_recorder"]
