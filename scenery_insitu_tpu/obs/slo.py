"""Live SLO engine: rolling-window latency objectives checked ON the run.

The Recorder (obs/recorder.py) answers "what happened" after the fact;
this module answers "are we inside budget" while frames are still being
delivered. A ``SLOEngine`` holds one rolling window per metric
(``frame_ms``, ``staleness_frames``, ``camera_to_pixel_ms`` and
per-phase ``phase:<name>_ms``), computes p50/p99 by nearest-rank over
the window, and compares the p99 against the budget from the
``FrameworkConfig.slo`` block.

Breach semantics (docs/OBSERVABILITY.md "SLO engine"): a breach fires on
the TRANSITION of a metric's rolling p99 across its budget, not on every
over-budget sample — one typed ``slo_breach`` instant event, one
``slo_breaches`` counter bump, and one deduped ``slo.breach`` ledger row
per episode; the metric re-arms when its p99 returns under budget.
Budgets of 0 disable the gate but the estimator still tracks the metric,
so ``snapshot()`` is a complete machine-readable health record either
way — the signal the relay tree's admission/autoscale (ROADMAP item 2)
and the elastic fleet's frames-to-recover gate (item 5) consume.

Everything here is stdlib-only and O(window log window) worst case per
check (a sort of <= ``slo.window`` floats), so it is safe on the frame
loop.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

from scenery_insitu_tpu.obs import recorder as _rec

# Constant by design: the ledger dedupes on (component, from, to,
# reason), so a per-metric reason string would bloat it.
_BREACH_REASON = ("rolling p99 crossed its configured budget "
                  "(docs/OBSERVABILITY.md 'SLO engine')")


def _nearest_rank(sorted_vals, q: float) -> float:
    """Nearest-rank quantile over a pre-sorted sequence."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    idx = min(n - 1, max(0, int(q * n + 0.5) - 1))
    return sorted_vals[idx]


class _Metric:
    __slots__ = ("name", "budget", "buf", "n_total", "last",
                 "breached", "breaches")

    def __init__(self, name: str, budget: float, window: int):
        self.name = name
        self.budget = budget          # 0 = tracked, not gated
        self.buf = deque(maxlen=window)
        self.n_total = 0
        self.last = 0.0
        self.breached = False
        self.breaches = 0


class SLOEngine:
    """Rolling-window SLO checks over live run metrics.

    ``observe(metric, value)`` is the whole write API; budgets come from
    the config block, unknown metrics are tracked gate-free, and
    ``snapshot()`` is the read API (JSON-able)."""

    #: metric name -> SLOConfig budget field
    _BUDGET_FIELDS = {
        "frame_ms": "frame_p99_ms",
        "staleness_frames": "staleness_p99_frames",
        "camera_to_pixel_ms": "camera_to_pixel_p99_ms",
        "delivery_lag_ms": "delivery_lag_p99_ms",
    }

    def __init__(self, cfg, recorder: Optional[_rec.Recorder] = None):
        self.cfg = cfg
        self.enabled = bool(cfg.enabled)
        self.window = int(cfg.window)
        self.min_samples = int(cfg.min_samples)
        self._recorder = recorder
        self._metrics: Dict[str, _Metric] = {}

    # ------------------------------------------------------------- write
    def _budget_for(self, metric: str) -> float:
        field = self._BUDGET_FIELDS.get(metric)
        if field is not None:
            return float(getattr(self.cfg, field))
        if metric.startswith("phase:"):
            return float(self.cfg.phase_p99_ms)
        return 0.0

    def observe(self, metric: str, value: float,
                frame: Optional[int] = None) -> None:
        """Feed one sample; runs the breach check once the window holds
        ``min_samples``. No-op when the engine is disabled."""
        if not self.enabled:
            return
        m = self._metrics.get(metric)
        if m is None:
            m = self._metrics[metric] = _Metric(
                metric, self._budget_for(metric), self.window)
        m.buf.append(float(value))
        m.n_total += 1
        m.last = float(value)
        if m.budget <= 0 or len(m.buf) < self.min_samples:
            return
        p99 = _nearest_rank(sorted(m.buf), 0.99)
        if p99 > m.budget:
            if not m.breached:
                m.breached = True
                m.breaches += 1
                self._mint_breach(m, p99, frame)
        else:
            m.breached = False          # re-arm for the next episode

    def _mint_breach(self, m: _Metric, p99: float,
                     frame: Optional[int]) -> None:
        rec = self._recorder or _rec.get_recorder()
        rec.count("slo_breaches")
        rec.event("slo_breach", frame=frame, metric=m.name,
                  p99=round(p99, 3), budget=m.budget,
                  window_n=len(m.buf))
        _rec.degrade("slo.breach", m.name, "breached", _BREACH_REASON,
                     warn=False)

    def observe_phase(self, name: str, seconds: float,
                      frame: Optional[int] = None) -> None:
        """Per-phase budget feed (``slo.phase_p99_ms``), in seconds to
        match Timers.record."""
        self.observe(f"phase:{name}_ms", seconds * 1e3, frame=frame)

    # -------------------------------------------------------------- read
    def quantile(self, metric: str, q: float) -> float:
        m = self._metrics.get(metric)
        return _nearest_rank(sorted(m.buf), q) if m else 0.0

    def breached(self, metric: Optional[str] = None) -> bool:
        """Currently-breached state of one metric (or any, when None)."""
        if metric is not None:
            m = self._metrics.get(metric)
            return bool(m and m.breached)
        return any(m.breached for m in self._metrics.values())

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able health record: per-metric rolling p50/p99 against
        budget, current breach state and total breach episodes. This is
        the machine-readable signal downstream controllers poll."""
        metrics = {}
        for name, m in sorted(self._metrics.items()):
            s = sorted(m.buf)
            metrics[name] = {
                "n": m.n_total,
                "window_n": len(s),
                "last": round(m.last, 3),
                "p50": round(_nearest_rank(s, 0.50), 3),
                "p99": round(_nearest_rank(s, 0.99), 3),
                "budget": m.budget,
                "breached": m.breached,
                "breaches": m.breaches,
            }
        return {"type": "slo_report", "enabled": self.enabled,
                "window": self.window, "min_samples": self.min_samples,
                "metrics": metrics,
                "total_breaches": sum(m.breaches
                                      for m in self._metrics.values()),
                "healthy": not any(m.breached
                                   for m in self._metrics.values())}
