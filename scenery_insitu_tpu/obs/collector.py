"""Fleet telemetry side-channel: per-process obs batches -> ONE trace.

PR-3's Recorder is process-local and post-hoc; a multi-process fleet
(render ranks -> head -> serve, PR 13/14) therefore has no cross-process
answer to "where did this frame's time go". This module closes that gap
with three small pieces (docs/OBSERVABILITY.md "Fleet tracing"):

- **trace context** helpers (``trace_ctx``/``lineage``): a compact dict
  ``{"frame", "src", "t"}`` threaded through every wire header that
  carries frame bytes. Senders stamp it, receivers mint a ``lineage``
  instant event; the merged trace joins those instants into flow arrows
  following a frame's sim -> march -> exchange -> composite -> publish
  -> serve -> viewer arc.
- **ObsPublisher**: each process PUBs its Recorder's event backlog as
  batched, zlib-compressed JSON over ZMQ, and pings the collector's
  heartbeat ROUTER from a DEALER to estimate its clock offset
  (``offset = tc - (t0 + rtt/2)``, error bound ±rtt/2). Loss-tolerant
  by construction: every socket op is non-blocking with a small HWM — a
  dead or slow collector costs dropped batches (counted and ledgered
  ``obs.collector``), never a stalled render loop.
- **Collector**: binds the SUB + ROUTER pair, drains batches, answers
  pings with its own clock, and merges everything into a single
  multi-track Perfetto trace (pid = rank) on the collector's timebase,
  with flow events binding each frame's lineage across process tracks.

Import is JAX-free and zmq-lazy so any module can use the helpers.
"""

from __future__ import annotations

import json
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from scenery_insitu_tpu.obs import recorder as _rec

_DROP_REASON = ("collector unreachable or slow (non-blocking send "
                "would block, or heartbeats unanswered); telemetry "
                "batch dropped, render loop unaffected")
_NOZMQ_REASON = "pyzmq unavailable; fleet telemetry side-channel is inert"

# Stages in canonical arc order — used only for stable tie-breaks when
# two lineage instants land on the same aligned microsecond.
ARC_ORDER = ("sim", "march", "exchange", "composite", "publish",
             "tile", "head", "serve", "video", "viewer")


def trace_ctx(frame: int, src: int) -> Dict[str, Any]:
    """The wire trace context: frame id, origin rank, origin wall
    clock. Senders embed it under the ``"tc"`` header key; decoders that
    predate it ignore unknown keys, so the wire stays compatible."""
    return {"frame": int(frame), "src": int(src),
            "t": round(time.time(), 6)}


def lineage(stage: str, role: str, frame: Optional[int],
            ctx: Optional[dict] = None,
            rec: Optional[_rec.Recorder] = None, **attrs) -> None:
    """Mint one ``lineage`` instant on the active recorder: ``stage`` is
    the arc hop (publish/serve/...), ``role`` is ``"send"`` or
    ``"recv"``. A receive with the sender's ``ctx`` also records the
    origin stamp and the wall-clock age of the bytes — the raw material
    for cross-process flow arrows and camera-to-pixel spans."""
    rec = rec or _rec.get_recorder()
    if not rec.enabled:
        return
    if ctx:
        frame = ctx.get("frame", frame)
        attrs["src"] = ctx.get("src")
        t0 = ctx.get("t")
        if t0:
            attrs["t_origin"] = t0
            attrs["age_ms"] = round((time.time() - t0) * 1e3, 3)
    rec.event("lineage", frame=frame, stage=stage, role=role, **attrs)


# ------------------------------------------------------------- publisher

class ObsPublisher:
    """Per-process telemetry publisher. ``pump(recorder)`` on the frame
    loop ships the recorder's new events since the last pump; everything
    is non-blocking and drop-on-pressure."""

    def __init__(self, endpoint: str, hb_endpoint: str = "",
                 rank: int = 0, interval_s: float = 0.25,
                 max_batch_events: int = 10_000):
        self.rank = rank
        self.interval_s = interval_s
        self.max_batch_events = max_batch_events
        self.clock_offset = 0.0     # collector clock minus local clock
        self.rtt = 0.0              # of the offset sample kept (min-RTT)
        self.batches = 0
        self.drops = 0
        self._cursor = 0
        self._seq = 0
        self._last_pump = 0.0
        self._unanswered = 0    # pings sent since the last pong
        self._seen = set()      # ranks the collector reports ingested
        self._pub = self._hb = self._ctx = None
        try:
            import zmq
        except ImportError:
            _rec.degrade("obs.collector", "publish", "disabled",
                         _NOZMQ_REASON, warn=False)
            return
        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.setsockopt(zmq.SNDHWM, 16)
        self._pub.setsockopt(zmq.LINGER, 0)
        self._pub.connect(endpoint)
        if hb_endpoint:
            self._hb = self._ctx.socket(zmq.DEALER)
            self._hb.setsockopt(zmq.SNDHWM, 4)
            self._hb.setsockopt(zmq.LINGER, 0)
            self._hb.connect(hb_endpoint)

    # ------------------------------------------------------------ clocks
    def _heartbeat(self) -> None:
        """Fire one ping and drain pongs; keep the min-RTT offset sample
        (offset error is bounded by ±rtt/2, see docs). The post-ping
        wait is bounded at 5 ms: a live collector answers on loopback/
        ICI well inside it (giving an honest RTT instead of one inflated
        by the pump interval), a dead one costs 5 ms per interval_s."""
        zmq, hb = self._zmq, self._hb
        try:
            hb.send(json.dumps({"t0": time.time()}).encode(),
                    zmq.NOBLOCK)
            self._unanswered += 1
        except zmq.ZMQError:
            # HWM of queued pings reached — as unanswered as they come
            self._unanswered += 1
        waited = False
        while True:
            try:
                raw = hb.recv(zmq.NOBLOCK)
            except zmq.ZMQError:
                if waited or not hb.poll(5):
                    break
                waited = True
                continue
            t1 = time.time()
            try:
                pong = json.loads(raw)
            except ValueError:
                continue
            self._unanswered = 0
            self._seen = set(pong.get("seen", []))
            rtt = t1 - pong["t0"]
            if rtt >= 0 and (self.rtt == 0.0 or rtt < self.rtt):
                self.rtt = rtt
                self.clock_offset = pong["tc"] - (pong["t0"] + rtt / 2)

    @property
    def linked(self) -> bool:
        """True once a heartbeat pong proved the collector ingested a
        batch (or probe) from THIS rank — the PUB path is established
        end to end. The channel stays loss-legal either way; ``linked``
        exists so a caller that NEEDS a deterministic start (the traced-
        fleet drill, a bench run) can sequence one with ``probe()``
        instead of sacrificing the first batch to the asynchronous zmq
        subscription handshake."""
        return self.rank in self._seen

    def probe(self) -> None:
        """Ship one contentless presence batch + heartbeat. Costs a few
        bytes, moves no events, advances no cursor — loop it until
        ``linked`` (the collector's host must be polling)."""
        if self._pub is None:
            return
        if self._hb is not None:
            self._heartbeat()
        payload = zlib.compress(json.dumps(
            {"rank": self.rank, "probe": True}).encode(), 1)
        try:
            self._pub.send(payload, self._zmq.NOBLOCK)
        except self._zmq.ZMQError:
            pass

    # -------------------------------------------------------------- pump
    def pump(self, recorder: _rec.Recorder, force: bool = False) -> bool:
        """Publish events accumulated since the last pump (rate-limited
        to ``interval_s`` unless ``force``). Returns True when a batch
        was handed to the socket, False on skip/drop — never raises,
        never blocks."""
        if self._pub is None:
            return False
        now = time.monotonic()
        if not force and now - self._last_pump < self.interval_s:
            return False
        self._last_pump = now
        if self._hb is not None:
            self._heartbeat()
        events = recorder.events[self._cursor:
                                 self._cursor + self.max_batch_events]
        self._cursor += len(events)
        self._seq += 1
        batch = {"rank": self.rank, "seq": self._seq,
                 "epoch_unix": recorder.epoch_unix,
                 "t_unix": time.time(),
                 "clock_offset": round(self.clock_offset, 6),
                 "rtt": round(self.rtt, 6),
                 "events": events,
                 "counters": dict(recorder.counters),
                 "ledger": _rec.ledger()}
        payload = zlib.compress(json.dumps(batch).encode(), 1)
        try:
            self._pub.send(payload, self._zmq.NOBLOCK)
        except self._zmq.ZMQError:
            # HWM full: the batch is lost, the loop is not.
            self._drop(recorder)
            return False
        if self._hb is not None and self._unanswered >= 3:
            # a PUB socket discards silently when the peer is gone, so a
            # dead collector never raises — three consecutive unanswered
            # heartbeats is the presumed-lost verdict for this batch
            self._drop(recorder)
            return False
        self.batches += 1
        recorder.count("obs_batches_published")
        return True

    def _drop(self, recorder: _rec.Recorder) -> None:
        self.drops += 1
        recorder.count("obs_batch_drops")
        _rec.degrade("obs.collector", "publish", "drop",
                     _DROP_REASON, warn=False)

    def close(self, recorder: Optional[_rec.Recorder] = None) -> None:
        """Final forced pump, then tear the sockets down."""
        if recorder is not None:
            self.pump(recorder, force=True)
        for s in (self._pub, self._hb):
            if s is not None:
                s.close(0)
        self._pub = self._hb = None


# ------------------------------------------------------------- collector

class Collector:
    """The fleet-side aggregator. Bind, ``poll()`` on any schedule, then
    ``export_fleet_trace()``; a collector that is never polled (or dies)
    costs publishers nothing but drops."""

    def __init__(self, bind: str = "tcp://127.0.0.1",
                 endpoint: str = "", hb_endpoint: str = ""):
        import zmq              # the collector side genuinely needs zmq
        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._sub = self._ctx.socket(zmq.SUB)
        self._sub.setsockopt(zmq.SUBSCRIBE, b"")
        self._sub.setsockopt(zmq.LINGER, 0)
        self._hb = self._ctx.socket(zmq.ROUTER)
        self._hb.setsockopt(zmq.LINGER, 0)
        if endpoint:
            self._sub.bind(endpoint)
            self.endpoint = endpoint
        else:
            port = self._sub.bind_to_random_port(bind)
            self.endpoint = f"{bind}:{port}"
        if hb_endpoint:
            self._hb.bind(hb_endpoint)
            self.hb_endpoint = hb_endpoint
        else:
            port = self._hb.bind_to_random_port(bind)
            self.hb_endpoint = f"{bind}:{port}"
        self._poller = zmq.Poller()
        self._poller.register(self._sub, zmq.POLLIN)
        self._poller.register(self._hb, zmq.POLLIN)
        # rank -> merged per-process record
        self.ranks: Dict[int, Dict[str, Any]] = {}
        self.batches = 0
        self.decode_errors = 0

    # -------------------------------------------------------------- poll
    def poll(self, timeout_ms: int = 50) -> int:
        """Drain pending batches and answer pings; returns the number of
        batches ingested this call."""
        zmq = self._zmq
        got = 0
        ready = dict(self._poller.poll(timeout_ms))
        while ready:
            if self._hb in ready:
                try:
                    ident, raw = self._hb.recv_multipart(zmq.NOBLOCK)
                    ping = json.loads(raw)
                    self._hb.send_multipart(
                        [ident, json.dumps(
                            {"t0": ping["t0"],
                             "tc": time.time(),
                             "seen": sorted(self.ranks)}).encode()],
                        zmq.NOBLOCK)
                except (zmq.ZMQError, ValueError, KeyError):
                    self.decode_errors += 1
            if self._sub in ready:
                try:
                    raw = self._sub.recv(zmq.NOBLOCK)
                    self._ingest(json.loads(zlib.decompress(raw)))
                    got += 1
                except (zmq.ZMQError, ValueError, KeyError,
                        zlib.error):
                    self.decode_errors += 1
            ready = dict(self._poller.poll(0))
        return got

    def _ingest(self, batch: dict) -> None:
        rank = int(batch["rank"])
        r = self.ranks.setdefault(rank, {"events": [], "batches": 0})
        if batch.get("probe"):          # presence only — no payload
            return
        r["events"].extend(batch.get("events") or [])
        r["batches"] += 1
        for k in ("epoch_unix", "clock_offset", "rtt", "counters",
                  "ledger", "seq", "t_unix"):
            if k in batch:
                r[k] = batch[k]
        self.batches += 1

    # ------------------------------------------------------------- merge
    def _aligned_us(self, r: dict, ev: dict) -> float:
        """Event time on the collector's unix clock, in µs. Alignment
        model: local unix = epoch_unix + ts; collector unix = local +
        clock_offset (error bounded by ±rtt/2 of the kept sample)."""
        t = r.get("epoch_unix", 0.0) + ev["ts"] + r.get(
            "clock_offset", 0.0)
        return t * 1e6

    def merged_events(self) -> List[dict]:
        """All ranks' raw events with aligned ``t_us`` (collector unix
        µs) attached, time-sorted."""
        out = []
        for rank, r in self.ranks.items():
            for ev in r["events"]:
                e = dict(ev)
                e["rank"] = rank
                e["t_us"] = self._aligned_us(r, ev)
                out.append(e)
        out.sort(key=lambda e: e["t_us"])
        return out

    def frame_arc(self, frame: int) -> List[dict]:
        """One frame's lineage instants across every rank, in aligned
        time order (canonical-arc tie-break) — the per-frame causal
        timeline the flow arrows draw."""
        hops = [e for e in self.merged_events()
                if e["type"] == "instant" and e["name"] == "lineage"
                and e.get("frame") == frame]

        def key(e):
            stage = (e.get("attrs") or {}).get("stage", "")
            rank = ARC_ORDER.index(stage) if stage in ARC_ORDER else 99
            return (e["t_us"], rank)
        hops.sort(key=key)
        return hops

    def frames_seen(self) -> List[int]:
        return sorted({e.get("frame") for e in self.merged_events()
                       if e["type"] == "instant"
                       and e["name"] == "lineage"
                       and e.get("frame") is not None})

    # ------------------------------------------------------------ export
    def trace_events(self) -> List[dict]:
        """The merged multi-track Perfetto event list: every rank's
        spans/counters/instants on the collector timebase (pid = rank),
        plus flow arrows (ph "s"/"f") binding each frame's lineage hops
        across tracks, plus each rank's final ledger."""
        t0_us = None
        merged = self.merged_events()
        if merged:
            t0_us = min(e["t_us"] for e in merged)
        out = []
        for rank, r in sorted(self.ranks.items()):
            out.append({"ph": "M", "name": "process_name", "pid": rank,
                        "tid": 0, "args": {"name": f"rank {rank}"}})
        for ev in merged:
            ts = round(ev["t_us"] - t0_us, 1)
            base = {"name": ev["name"], "pid": ev["rank"], "tid": 0,
                    "ts": ts}
            args = dict(ev.get("attrs") or {})
            if "frame" in ev:
                args["frame"] = ev["frame"]
            if ev["type"] == "span":
                base.update(ph="X", dur=round(ev["dur"] * 1e6, 1),
                            cat="phase")
                if "parent" in ev:
                    args["parent"] = ev["parent"]
            elif ev["type"] == "counter":
                base.update(ph="C", cat="counter")
                args = {"value": ev["value"]}
            else:
                base.update(ph="i", s="p", cat="event")
            base["args"] = args
            out.append(base)
        # Flow arrows: consecutive lineage hops of each frame.
        for frame in self.frames_seen():
            hops = self.frame_arc(frame)
            for k in range(len(hops) - 1):
                a, b = hops[k], hops[k + 1]
                fid = f"f{frame}.{k}"
                out.append({"ph": "s", "id": fid, "cat": "lineage",
                            "name": f"frame {frame}",
                            "pid": a["rank"], "tid": 0,
                            "ts": round(a["t_us"] - t0_us, 1)})
                out.append({"ph": "f", "bp": "e", "id": fid,
                            "cat": "lineage", "name": f"frame {frame}",
                            "pid": b["rank"], "tid": 0,
                            "ts": round(b["t_us"] - t0_us, 1)})
        for rank, r in sorted(self.ranks.items()):
            for entry in r.get("ledger") or []:
                out.append({"ph": "i", "s": "g",
                            "name": f"degrade:{entry['component']}",
                            "pid": rank, "tid": 0, "ts": 0.0,
                            "cat": "degrade", "args": entry})
        return out

    def clock_model(self) -> Dict[str, Any]:
        """Per-rank alignment record: offset to the collector clock,
        the RTT of the sample it came from, and the resulting error
        bound (±rtt/2, ms)."""
        return {str(rank): {
                    "clock_offset_s": r.get("clock_offset", 0.0),
                    "rtt_s": r.get("rtt", 0.0),
                    "error_bound_ms": round(
                        r.get("rtt", 0.0) / 2 * 1e3, 3)}
                for rank, r in sorted(self.ranks.items())}

    def export_fleet_trace(self, path: str) -> str:
        """Write the ONE merged fleet trace (open at ui.perfetto.dev)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.trace_events(),
                       "displayTimeUnit": "ms",
                       "otherData": {"fleet": True,
                                     "ranks": sorted(self.ranks),
                                     "batches": self.batches,
                                     "decode_errors": self.decode_errors,
                                     "clock_model": self.clock_model()}},
                      f)
        return path

    def summary(self) -> Dict[str, Any]:
        return {"ranks": sorted(self.ranks),
                "batches": self.batches,
                "decode_errors": self.decode_errors,
                "events": sum(len(r["events"])
                              for r in self.ranks.values()),
                "clock_model": self.clock_model()}

    def close(self) -> None:
        for s in (self._sub, self._hb):
            s.close(0)
