"""Phase attribution inside compiled steps.

The distributed frame is ONE jitted SPMD program by design (XLA overlaps
march, exchange and composite), so host-side spans can only see
dispatch+fetch — the march/exchange/merge split inside the step is
invisible to every timer the repo has. This module makes the device
explain itself:

1. Every step builder in ``parallel/pipeline.py`` (plus hier.py,
   ops/composite.py and models/pipelines.py) wraps its phases in
   ``phase(name)`` — a ``jax.named_scope`` with the ``sitpu_`` prefix.
   XLA carries the scope through fusion into per-instruction
   ``op_name`` metadata in the compiled HLO.
2. ``ProfileCapture`` runs N bracketed frames under
   ``jax.profiler.trace``, parses the emitted trace-event JSON
   (``plugins/profile/<ts>/*.trace.json.gz``), and joins each XLA op
   event back to its scope via the compiled HLO text: instruction names
   are module-unique and the trace events carry ``args.hlo_op`` +
   ``args.hlo_module``. This join is backend-portable — it works on the
   CPU trace backend today and on TPU XSpace-derived traces unchanged.

Accounting (validated against an 8-device virtual-mesh probe):

- events are NOT duplicated per pooled runtime thread — one event per
  (op, device, frame) — so per-phase ms = Σ dur / (frames × devices);
- scan-body ops legitimately recur per iteration, which total-sum
  accounting handles for free;
- the innermost (**last**) ``sitpu_`` component of an op_name wins, so
  an outer ``sitpu_wave`` scope never subsumes the march/exchange
  scopes nested inside it;
- device time the scopes don't explain lands in ``unattributed``; the
  gap between wall-clock and total device time lands in ``host`` (one
  of the roofline bound classes); when an intra-op thread pool makes
  summed op time EXCEED wall (CPU backends), the breakdown is
  normalized onto the wall (``normalized: true``, raw ratio kept in
  ``op_parallelism``) — so the per-phase sum matches the measured step
  wall-clock by construction and ``coverage`` records how much of the
  wall the device actually explained.

Module-level ``import jax`` is intentional: only JAX-bearing code
(pipeline builders, bench children, tests) imports this file; the
JAX-free artifact consumers live in obs/roofline.py and
benchmarks/divergence.py.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import tempfile
import time
from typing import Any, Callable, Dict, Optional

import jax

from scenery_insitu_tpu.obs import recorder as _rec

SCOPE_PREFIX = "sitpu_"

# The phase catalog — every named scope the step builders emit. Tests
# assert per-builder subsets of these appear in lowered HLO; the CI
# attribution lane asserts the captured breakdown names come from here
# (plus the two synthetic phases the capture itself mints).
PHASES = ("march", "halo", "exchange", "merge", "resegment",
          "wire_encode", "sim_step", "dcn_hop", "wave")

# Synthetic phases ProfileCapture adds on top of the scope catalog.
EXTRA_PHASES = ("unattributed", "host")


def phase(name: str):
    """Named scope for one step phase — ``with phase("march"): ...``.
    Zero runtime cost inside jit (it only tags HLO metadata)."""
    return jax.named_scope(SCOPE_PREFIX + name)


def scope_of(op_name: str) -> Optional[str]:
    """Extract the phase from an HLO ``op_name`` metadata path. The LAST
    ``sitpu_`` component wins so nested scopes attribute to the
    innermost phase (wave(march) → march)."""
    found = None
    for comp in op_name.split("/"):
        if comp.startswith(SCOPE_PREFIX):
            found = comp[len(SCOPE_PREFIX):]
    return found


def scope_names(text: str) -> set:
    """All ``sitpu_*`` phase names present in an HLO / StableHLO dump —
    works on both ``lower().as_text()`` (loc metadata) and
    ``compile().as_text()`` (op_name metadata)."""
    return set(re.findall(r"sitpu_(\w+)", text))


_HLO_MODULE_RE = re.compile(r"^HloModule ([^,\s]+)", re.M)
_HLO_OP_RE = re.compile(
    r"%?([\w\.\-]+) = [^\n]*?metadata=\{[^}]*?op_name=\"([^\"]*)\"")


def parse_hlo_scopes(hlo_text: str):
    """(module_name, {instruction_name: phase}) from compiled HLO text.
    Instruction names are module-unique, so they key the trace join."""
    m = _HLO_MODULE_RE.search(hlo_text)
    module = m.group(1) if m else None
    ops: Dict[str, str] = {}
    for inst, op_name in _HLO_OP_RE.findall(hlo_text):
        sc = scope_of(op_name)
        if sc is not None:
            ops[inst] = sc
    return module, ops


def _trace_events(trace_dir: str):
    """Load the newest emitted trace under ``trace_dir`` and yield its
    complete ("X") events. jax.profiler.trace writes
    ``<dir>/plugins/profile/<ts>/<host>.trace.json.gz`` on every
    backend that supports tracing (CPU included)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json*")))
    if not paths:
        raise FileNotFoundError(
            f"no trace emitted under {trace_dir!r} (profiler backend "
            "absent?)")
    newest_run = os.path.dirname(paths[-1])
    for path in paths:
        if os.path.dirname(path) != newest_run:
            continue
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "X":
                yield ev


class ProfileCapture:
    """Run N traced frames of a compiled step and attribute device time
    back to the ``sitpu_*`` phase scopes.

    ``capture(fn, *args, step=None)``:

    - ``fn`` must be jitted (it is lowered via ``fn.lower(*args)`` to
      get the compiled HLO — lowering is abstract, so donated buffers
      are fine);
    - ``step`` optionally runs ONE frame (a zero-arg callable returning
      something blockable). Required when ``fn`` donates its inputs and
      the caller threads state between frames (bench.py); when omitted,
      frames are ``fn(*args)``.

    ``host_time_fn`` (zero-arg, returns CUMULATIVE host seconds) lets
    the caller attribute measured host-side work — e.g. the delivery
    plane's encode/compress/sink time accumulated inside ``step`` — to
    the ``host`` phase explicitly. Without it, CPU backends structurally
    report ``host: 0``: the intra-op pool makes summed device-op time
    exceed wall, the breakdown is normalized onto the WHOLE wall, and
    host = wall - device vanishes. With the hook, device phases
    normalize onto (wall - hooked host) instead, so the host-delivery
    share survives normalization and the divergence engine can model it
    (docs/OBSERVABILITY.md "Divergence engine").

    Disabled captures return None without touching the profiler, the
    trace machinery or the step — the zero-overhead path. Failures
    degrade through the ``obs.profiler`` ledger component and also
    return None; they never take the caller down.
    """

    def __init__(self, frames: int = 3, enabled: bool = True,
                 trace_dir: Optional[str] = None, warmup: int = 1,
                 devices: Optional[int] = None,
                 host_time_fn: Optional[Callable[[], float]] = None):
        self.frames = max(1, int(frames))
        self.enabled = bool(enabled)
        self.trace_dir = trace_dir
        self.warmup = max(0, int(warmup))
        self.devices = devices
        self.host_time_fn = host_time_fn

    def capture(self, fn, *args,
                step: Optional[Callable[[], Any]] = None
                ) -> Optional[Dict[str, Any]]:
        if not self.enabled:
            return None
        try:
            return self._capture(fn, args, step)
        except Exception as e:          # noqa: BLE001 — capture is
            # best-effort observability; the step being profiled must
            # keep running whatever the trace backend did
            _rec.degrade("obs.profiler", "device_trace", "none",
                         f"profile capture failed: {e}", warn=False)
            return None

    # ------------------------------------------------------------------
    def _capture(self, fn, args, step):
        hlo = fn.lower(*args).compile().as_text()
        module, op_scopes = parse_hlo_scopes(hlo)

        run = step if step is not None else (
            lambda: jax.block_until_ready(fn(*args)))
        for _ in range(self.warmup):
            jax.block_until_ready(run())

        trace_dir = self.trace_dir or tempfile.mkdtemp(
            prefix="sitpu_profile_")
        h0 = self.host_time_fn() if self.host_time_fn else 0.0
        t0 = time.perf_counter()
        with jax.profiler.trace(trace_dir):
            for _ in range(self.frames):
                jax.block_until_ready(run())
        wall_ms = (time.perf_counter() - t0) * 1e3 / self.frames
        hook_ms = 0.0
        if self.host_time_fn:
            hook_ms = max(0.0, (self.host_time_fn() - h0) * 1e3
                          / self.frames)
            hook_ms = min(hook_ms, wall_ms)   # a hook cannot exceed wall

        phase_us: Dict[str, float] = {}
        phase_events: Dict[str, int] = {}
        total_events = joined = 0
        for ev in _trace_events(trace_dir):
            ev_args = ev.get("args") or {}
            if module is not None and ev_args.get(
                    "hlo_module") not in (None, module):
                continue
            op = ev_args.get("hlo_op") or ev.get("name")
            if op is None:
                continue
            total_events += 1
            sc = op_scopes.get(op)
            if sc is None:
                sc = "unattributed"
            else:
                joined += 1
            phase_us[sc] = phase_us.get(sc, 0.0) + float(
                ev.get("dur") or 0.0)
            phase_events[sc] = phase_events.get(sc, 0) + 1

        devices = self.devices or jax.local_device_count()
        phases = {
            name: {"ms": round(us / 1e3 / (self.frames * devices), 4),
                   "events": phase_events.get(name, 0)}
            for name, us in sorted(phase_us.items())}
        device_ms = sum(p["ms"] for p in phases.values())
        # CPU runtimes execute ops across an intra-op thread pool, so
        # summed op time can exceed wall-clock (parallelism > 1); a TPU
        # core's timeline is serialized, so this is a no-op there. The
        # breakdown is normalized onto the wall MINUS the hooked host
        # time (measured host work is not the device's to claim) so the
        # per-phase sum matches the measured step wall-clock by
        # construction; op_parallelism keeps the raw ratio honest.
        device_budget = max(0.0, wall_ms - hook_ms)
        op_parallelism = (device_ms / device_budget
                          if device_budget > 0 else None)
        normalized = False
        if op_parallelism is not None and op_parallelism > 1.0:
            scale = device_budget / device_ms
            for p in phases.values():
                p["ms"] = round(p["ms"] * scale, 4)
            device_ms = sum(p["ms"] for p in phases.values())
            normalized = True
        host_ms = hook_ms + max(0.0, wall_ms - hook_ms - device_ms)
        phases["host"] = {"ms": round(host_ms, 4), "events": 0}

        attr = {
            "type": "phase_attribution",
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "hlo_module": module,
            "frames": self.frames,
            "devices": devices,
            "wall_ms_per_frame": round(wall_ms, 4),
            "device_ms_per_frame": round(device_ms, 4),
            "host_hook_ms_per_frame": round(hook_ms, 4),
            "coverage": (round(min(1.0, op_parallelism), 4)
                         if op_parallelism is not None else None),
            "op_parallelism": (round(op_parallelism, 4)
                               if op_parallelism is not None else None),
            "normalized": normalized,
            "scoped_ops": len(op_scopes),
            "events_total": total_events,
            "events_joined": joined,
            "phases": phases,
        }
        _rec.get_recorder().count("profile_captures")
        return attr


# ------------------------------------------------- fleet-trace export

def attribution_chrome_events(attr: Dict[str, Any],
                              pid: int = 9000) -> list:
    """Render one attribution as extra Perfetto tracks: a synthetic
    "device phases" process whose complete events lay the per-phase ms
    out sequentially (one representative frame). Append these to a
    Recorder ``chrome_trace_events()`` list or an exported trace file
    (``append_to_chrome_trace``)."""
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "device phases (attributed)"}}]
    ts = 0.0
    for name, p in (attr.get("phases") or {}).items():
        dur = float(p.get("ms") or 0.0) * 1e3    # µs
        out.append({"ph": "X", "name": name, "pid": pid, "tid": 0,
                    "ts": round(ts, 1), "dur": round(dur, 1),
                    "cat": "device_phase",
                    "args": {"ms": p.get("ms"),
                             "events": p.get("events")}})
        ts += dur
    return out


def append_to_chrome_trace(attr: Dict[str, Any], path: str) -> str:
    """Append the attribution tracks to an existing exported fleet
    trace (Recorder.export_chrome_trace format)."""
    with open(path) as f:
        doc = json.load(f)
    doc.setdefault("traceEvents", []).extend(
        attribution_chrome_events(attr))
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def publish_attribution(attr: Dict[str, Any], rec=None,
                        frame: Optional[int] = None) -> None:
    """Publish a capture into the live fleet Recorder as an instant
    event carrying the per-phase breakdown (shows up in the PR-17
    Perfetto trace alongside the host-side spans)."""
    rec = rec or _rec.get_recorder()
    rec.event("phase_attribution", frame=frame,
              wall_ms_per_frame=attr.get("wall_ms_per_frame"),
              coverage=attr.get("coverage"),
              **{f"ms_{name}": p.get("ms")
                 for name, p in (attr.get("phases") or {}).items()})
