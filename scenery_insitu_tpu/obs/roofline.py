"""Roofline verdicts — per-phase achieved-vs-peak fractions and a bound
classification over a phase attribution (obs/profiler.py) joined with
the compiled step's device-cost snapshot (obs/device.py).

This module is deliberately **JAX-free**: bench.py's parent orchestrator
(which never touches a JAX backend) and benchmarks/divergence.py both
import it, and the peak tables here are the ONE copy the whole repo
reads (bench.py re-exports them for its MFU/HBM report fields).

The verdict model, stated so the artifact can carry its own assumptions:

- **Peaks** come from public per-device-kind numbers
  (``PEAK_TFLOPS`` / ``PEAK_HBM_GBPS`` by device-kind substring); link
  peaks default to the modeled-projection assumptions
  (``ICI_GBPS_DEFAULT`` / ``DCN_GBPS_DEFAULT`` — the same 45 / 3.125
  GB/s effective figures modeled_projection_r14.json uses). Non-TPU
  platforms get a stated ``cpu_nominal`` peak so CPU CI captures still
  produce *relative* verdicts — the artifact marks them indicative.
- **Apportionment**: XLA's cost analysis reports whole-step bytes/flops,
  not per-phase, so compute phases split the step totals proportionally
  to their measured ms share (communication and host phases excluded
  from the split). That is an assumption, written into the artifact.
- **Bound classification** per phase: ``host`` for the host-gap phase
  or any compute phase whose best achieved fraction sits under
  ``host_floor`` (nothing on the device explains the time), ``ici-dcn``
  for the exchange/DCN-hop phases, else the larger of the achieved HBM
  and MXU fractions (``hbm`` / ``mxu``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# TPU bf16 matmul peak FLOP/s by device-kind substring (public numbers).
PEAK_TFLOPS = (
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

# HBM bandwidth GB/s by device-kind substring (public numbers).
PEAK_HBM_GBPS = (
    ("v6", 1640.0), ("trillium", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0), ("v5 lite", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)

# Effective link peaks — the SAME figures the modeled projection assumes
# (benchmarks/results/modeled_projection_r14.json "assumptions"), so a
# divergence between modeled and measured exchange time is never an
# artifact of two different link models.
ICI_GBPS_DEFAULT = 45.0
DCN_GBPS_DEFAULT = 3.125

# Stated nominal peaks for non-TPU captures (one modern core's FMA rate
# and a laptop-class memory bus): absolute fractions are meaningless on
# the CPU fallback, but the RELATIVE ordering of phases still is — the
# artifact's peaks_source says which regime produced the verdicts.
CPU_NOMINAL_TFLOPS = 0.1
CPU_NOMINAL_HBM_GBPS = 20.0

# Phases whose time is a link transfer, not compute: classified ici-dcn
# against the matching link peak instead of the HBM/MXU roofline.
COMM_PHASES = {"exchange": "ici", "dcn_hop": "dcn"}


def kind_lookup(table, device_kind: str, platform: str,
                default: Optional[float]):
    """Device-kind substring lookup of a peak table; None off-TPU (the
    caller decides its non-TPU story), table default when the kind is
    unrecognized (assume v5e-class)."""
    if platform != "tpu":
        return None
    kind = (device_kind or "").lower()
    for sub, val in table:
        if sub in kind:
            return val
    return default


def peaks_for(device_kind: str, platform: str,
              ici_gbps: float = ICI_GBPS_DEFAULT,
              dcn_gbps: float = DCN_GBPS_DEFAULT) -> Dict[str, Any]:
    """The peak-assumption block of one capture: HBM + MXU peaks for the
    device kind (stated nominal figures off-TPU), link peaks from the
    modeled-projection assumptions. Every verdict artifact embeds this
    verbatim so the numbers can be re-judged when assumptions move."""
    tflops = kind_lookup(PEAK_TFLOPS, device_kind, platform, 197.0)
    hbm = kind_lookup(PEAK_HBM_GBPS, device_kind, platform, 819.0)
    if tflops is None or hbm is None:
        return {"tflops": CPU_NOMINAL_TFLOPS,
                "hbm_gbps": CPU_NOMINAL_HBM_GBPS,
                "ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps,
                "device_kind": device_kind, "platform": platform,
                "peaks_source": "cpu_nominal (relative verdicts only)"}
    return {"tflops": tflops, "hbm_gbps": hbm,
            "ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps,
            "device_kind": device_kind, "platform": platform,
            "peaks_source": "public device-kind table"}


def roofline_verdicts(attribution: Dict[str, Any],
                      cost: Optional[Dict[str, Any]] = None,
                      peaks: Optional[Dict[str, Any]] = None,
                      modeled: Optional[Dict[str, Any]] = None,
                      host_floor: float = 0.05) -> Dict[str, Any]:
    """Join a ``phase_attribution`` record (obs/profiler.py) with the
    step's cost snapshot into one verdict per phase: achieved-vs-peak
    HBM and MXU fractions and a bound classification (hbm / mxu /
    ici-dcn / host).

    ``modeled`` optionally supplies per-frame link bytes for the
    communication phases (``{"ici_bytes_per_frame": ...,
    "dcn_bytes_per_frame": ...}`` — e.g. from the modeled exchange
    traffic the step build minted) so the ici-dcn verdicts carry an
    achieved-GB/s figure too."""
    peaks = peaks or peaks_for("", "cpu")
    cost = cost if isinstance(cost, dict) else {}
    modeled = modeled or {}
    phases = attribution.get("phases") or {}
    wall = float(attribution.get("wall_ms_per_frame") or 0.0)
    step_bytes = float(cost.get("bytes_accessed") or 0.0)
    step_flops = float(cost.get("flops") or 0.0)
    compute_ms = sum(
        float(p.get("ms") or 0.0) for name, p in phases.items()
        if name not in COMM_PHASES and name != "host")
    verdicts: Dict[str, Any] = {}
    for name, p in phases.items():
        ms = float(p.get("ms") or 0.0)
        v: Dict[str, Any] = {
            "ms": round(ms, 4),
            "frac_of_wall": round(ms / wall, 4) if wall > 0 else None}
        if name == "host":
            v["bound"] = "host"
        elif name in COMM_PHASES:
            link = COMM_PHASES[name]
            peak_gbps = peaks.get(f"{link}_gbps")
            link_bytes = modeled.get(f"{link}_bytes_per_frame")
            if link_bytes and ms > 0:
                ach = float(link_bytes) / (ms / 1e3) / 1e9
                v["achieved_gbps"] = round(ach, 3)
                if peak_gbps:
                    v["link_frac_peak"] = round(ach / peak_gbps, 4)
            v["bound"] = "ici-dcn"
        else:
            share = ms / compute_ms if compute_ms > 0 else 0.0
            b_est = step_bytes * share
            f_est = step_flops * share
            hbm_frac = mxu_frac = None
            if ms > 0:
                if peaks.get("hbm_gbps"):
                    hbm_frac = (b_est / (ms / 1e3) / 1e9
                                ) / peaks["hbm_gbps"]
                if peaks.get("tflops"):
                    mxu_frac = (f_est / (ms / 1e3) / 1e12
                                ) / peaks["tflops"]
            v["bytes_est"] = round(b_est)
            v["flops_est"] = round(f_est)
            v["hbm_frac_peak"] = (round(hbm_frac, 4)
                                  if hbm_frac is not None else None)
            v["mxu_frac_peak"] = (round(mxu_frac, 4)
                                  if mxu_frac is not None else None)
            best = max(hbm_frac or 0.0, mxu_frac or 0.0)
            if best < host_floor:
                v["bound"] = "host"
            else:
                v["bound"] = ("hbm" if (hbm_frac or 0.0)
                              >= (mxu_frac or 0.0) else "mxu")
        verdicts[name] = v
    return {
        "type": "roofline_verdicts",
        "assumptions": {
            **peaks,
            "host_floor_frac": host_floor,
            "apportionment": (
                "whole-step cost-analysis bytes/flops split across "
                "compute phases proportionally to measured ms "
                "(communication + host phases excluded)"),
        },
        "step": {"bytes_accessed": step_bytes or None,
                 "flops": step_flops or None,
                 "wall_ms_per_frame": wall or None,
                 "cost_source": cost.get("source")},
        "verdicts": verdicts,
    }
