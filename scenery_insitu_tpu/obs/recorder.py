"""Structured run telemetry — the host half of the observability layer.

The reference instruments every render phase with hand-rolled nanoTime
spans and machine-greppable ``#COMP:rank:iter:sec#`` markers
(DistributedVolumeRenderer.kt:85-108, VDICompositingTest.kt:301);
``runtime/timers.py`` reproduces that. This module unifies those wall
-clock spans with everything the timers cannot say: WHICH frame and rank
a span belongs to, how often each executable (re)compiled, whether the
scan or the eager loop actually dispatched, and — through the fallback
ledger — every configured-but-degraded path of the run, as one
machine-readable record.

Three layers:

- ``Recorder``: structured span events (name, phase, frame, rank, t0,
  dur, attrs) plus counters and instant events. Every span also feeds a
  ``runtime.timers.Timers`` (O(1) PhaseStats, windowed dumps, ``#TAG#``
  markers) — the timers are one sink among several, and ``sess.timers``
  keeps working unchanged. A DISABLED recorder degrades to exactly the
  PR-1 behavior: spans still feed the timers but record no events and
  write no sinks (near-zero extra cost, no growing state).
- the module-level **fallback ledger** (`degrade`/`ledger`): process-
  global so probe-time degradations (Mosaic rejections fire inside
  cached compile probes, possibly before any session exists) are never
  lost. Identical (component, from, to, reason) entries are counted,
  not duplicated, and the first occurrence still emits the
  ``warnings.warn`` the call sites used to.
- exporters: Chrome-trace/Perfetto JSON (open ``trace.json`` at
  ``ui.perfetto.dev`` — complements the device-side
  ``jax.profiler.trace`` dir) and a JSONL metrics stream; the rank is in
  every event so multihost merges (parallel/multihost.gather_obs_events)
  are a concatenation.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from scenery_insitu_tpu.runtime.timers import Timers

# ---------------------------------------------------------------- ledger

_LEDGER: Dict[tuple, Dict[str, Any]] = {}
_LEDGER_LOCK = threading.Lock()


def degrade(component: str, from_: str, to: str, reason: str,
            warn: bool = True, stacklevel: int = 2) -> Dict[str, Any]:
    """Report one degradation: ``component`` was configured/asked to run
    ``from_`` but actually runs ``to`` because of ``reason``.

    Every silent-fallback site routes through here so a run can end with
    an explicit list of everything that did not run as configured
    (``ledger()``). The first occurrence of a (component, from, to,
    reason) tuple emits a ``warnings.warn`` — same visible behavior the
    inline warning sites had — and later occurrences only bump the
    entry's count (a per-frame fallback must not spam). The active
    recorder, if enabled, additionally gets an instant event so the
    degradation lands in the trace timeline too."""
    key = (component, from_, to, reason)
    with _LEDGER_LOCK:
        entry = _LEDGER.get(key)
        first = entry is None
        if first:
            entry = {"component": component, "from": from_, "to": to,
                     "reason": reason, "count": 1,
                     "t": round(time.time(), 3)}
            _LEDGER[key] = entry
        else:
            entry["count"] += 1
    if first and warn:
        import warnings
        warnings.warn(f"{component}: {from_} -> {to} ({reason})",
                      stacklevel=stacklevel + 1)
    rec = get_recorder()
    if rec.enabled:
        rec.event("degrade", component=component, **{"from": from_},
                  to=to, reason=reason)
    return entry


# The component catalog of every degrade() SITE in the repo — the static
# half of the ledger contract. sitpu-lint's SITPU-LEDGER checker
# discovers the sites by AST scan and tests/test_lint.py holds the two
# equal in both directions: a new degrade() call must register its
# component here (so docs/OBSERVABILITY.md stays complete), and a
# registry row without a live site is dead weight that must go. Keys are
# components, values say what degrading means there.
_LEDGER_REGISTRY: Dict[str, str] = {
    "bench.adaptive_mode": "bench: temporal adaptive mode needs the mxu "
                           "engine; histogram runs instead",
    "bricks.partition": "a brick render partition was configured where "
                        "the builder has no brick march (hybrid/plain "
                        "steps); the even z-slab decomposition renders",
    "bench.autotune_fold": "bench: a fold-autotune candidate crashed and "
                           "is dropped from the race",
    "bench.codec": "benchmarks: a codec under test is unavailable and "
                   "skipped (e.g. no native lz4 build)",
    "bench.config_run": "configs_bench: a per-config child run failed or "
                        "timed out; the artifact records an error row",
    "bench.cost_analysis": "bench: XLA cost analysis unavailable; "
                           "artifact bytes fall back to the floor model",
    "bench.platform": "bench/benchmarks: the TPU attempt gave way to the "
                      "CPU (or virtual-mesh) fallback",
    "bench.platform_attempt": "bench: one platform attempt failed "
                              "(per-attempt reason in failed_attempts)",
    "bench.scan_frames": "bench: SCAN_FRAMES requested without temporal "
                         "mxu mode; eager per-frame dispatch runs",
    "composite.schedule": "tile waves requested on a single-rank mesh; "
                          "frame schedule runs (nothing to overlap)",
    "config.removed_key": "a removed config key was set and ignored "
                          "(deprecation note in the reason)",
    "core.dataset_tf": "unknown dataset name; the generic gray-ramp "
                       "transfer function renders instead of a tuned one",
    "delta.reuse": "temporal fragment reuse requested where no marched "
                   "VDI fragment can be carried (gather/hybrid/plain/"
                   "particle modes, scan blocks); every frame "
                   "re-marches",
    "delivery.drain": "teardown drain of the async delivery queue timed "
                      "out; undelivered frames were abandoned so "
                      "shutdown could proceed",
    "delivery.encode": "parallel per-tile encode requested together "
                       "with temporal delta (stateful per-tile "
                       "history); the publisher encodes serially",
    "delivery.shed": "the bounded async delivery queue overflowed under "
                     "overflow='drop_oldest'; the stalest undelivered "
                     "frame was shed latest-wins",
    "divergence.modeled": "bench profiling: the model-vs-measured "
                          "divergence report could not be produced "
                          "(modeled projection missing or unreadable); "
                          "the attribution and roofline verdicts still "
                          "ride in the artifact (docs/OBSERVABILITY.md "
                          "'Divergence engine')",
    "head.rank_down": "head node: a render rank went silent past "
                      "stale_frames; frames composite without it "
                      "(degraded flag) until it returns",
    "ingest.stall": "shm ingest: no strictly-newer producer frame past "
                    "frame_timeout_ms; the session keeps rendering the "
                    "last-good frame until frames resume",
    "io.vdi_codec": "zstd codec unavailable; VDI IO degrades to stdlib "
                    "zlib",
    "lod.engine": "a multi-level brick map reached the gather engine, "
                  "which marches every brick at full resolution; levels "
                  "flatten to 0 (docs/PERF.md 'LOD marching')",
    "lod.inert": "lod.enabled is set but the session has no brick map "
                 "(composite.rebalance != bricks), so no per-brick "
                 "levels exist to plan; the replan is a no-op",
    "obs.collector": "fleet telemetry side-channel: a batch publish to "
                     "the collector could not complete without blocking "
                     "(dead/slow collector, HWM full); the batch is "
                     "dropped, the render loop never waits",
    "obs.flight_recorder": "an unhandled exception tore down a frame "
                           "loop; the last unflushed obs window was "
                           "dumped best-effort to the configured "
                           "trace/metrics paths",
    "obs.profiler": "a ProfileCapture could not produce a phase "
                    "attribution (trace backend absent, no trace "
                    "emitted, or the HLO/trace join failed); the step "
                    "keeps running unprofiled (docs/OBSERVABILITY.md "
                    "'Phase attribution')",
    "slo.breach": "the live SLO engine saw a rolling-window quantile "
                  "cross its configured budget (metric and quantile in "
                  "the reason); the run keeps going, the breach is the "
                  "signal",
    "regression.artifact": "regression_gate: a fresh bench artifact was "
                           "unreadable or had no recognized schema; it "
                           "is skipped, not silently passed",
    "regression.baseline": "regression_gate: a committed baseline is "
                           "missing or unrecognized for a requested "
                           "comparison; that comparison is skipped and "
                           "reported",
    "multihost.connect": "multihost.initialize could not reach the "
                         "coordinator on an attempt; retrying on the "
                         "bounded backoff ladder instead of hanging "
                         "the fleet silently",
    "multihost.host_down": "hierarchical head assembly: a host's domain "
                           "partial never arrived; the column block "
                           "composites without its slab content "
                           "(degraded), the frame still ships",
    "multihost.transport": "host gathers route through the coordinator "
                           "KV store because this backend cannot run "
                           "cross-process device collectives (the "
                           "multi-process CPU harness)",
    "occupancy.k_budget": "occupancy K budgets requested where no "
                          "pyramid/adaptive threshold exists; static "
                          "budgets run",
    "occupancy.ranges_remap": "sim-fused brick ranges coarsened onto an "
                              "incommensurate canonical grid (gcd bands)",
    "occupancy.rebalance": "render rebalancing requested where there is "
                           "nothing to rebalance (single rank / no "
                           "volume field); even z-slabs render",
    "occupancy.replan": "the render z-plan changed from fetched live "
                        "fractions; the affected steps recompile on the "
                        "new band split",
    "occupancy.sim_ranges": "fused-stencil ranges epilogue unavailable; "
                            "lax field_ranges recompute runs",
    "occupancy.vtiles_clamp": "requested in-plane occupancy tiles exceed "
                              "the geometry; clamped",
    "ops.composite_fold": "Mosaic rejected the fused composite resegment "
                          "kernel; XLA scan composite runs",
    "ops.count_fold": "Mosaic rejected the counting kernel; XLA counting "
                      "scan runs",
    "ops.march_fold": "Mosaic rejected the march fold kernel; XLA fold "
                      "runs",
    "ops.pallas_march.block_width": "kernel block width clamped below "
                                    "the VMEM-budget request",
    "ops.seg_fold": "Mosaic rejected a seg/fused fold kernel; the probed "
                    "seg stack runs",
    "phase_bench.sim_fused": "phase_bench: --sim-fused needs a 1-rank "
                             "mesh; xla_roll runs",
    "scenario.tf_update": "a steered transfer function not seen before "
                          "rebuilt the compiled steps (a repeated TF "
                          "restores its cached steps instead — the "
                          "recompile-or-reuse contract)",
    "session.scan_block": "a scan block fell back to eager frames "
                          "(regime change or steering drain)",
    "session.scan_frames": "scan_frames configured but unsupported in "
                           "this mode; eager loop runs",
    "serve.client": "edge server: a malformed or oversized client "
                    "message was dropped; the serve loop keeps going",
    "serve.shed": "edge server admission control refused a viewer or "
                  "camera request (max_viewers/queue_cap); the client "
                  "got a typed shed answer, not an exception",
    "serve.stale": "edge server answered from a VDI more than "
                   "serve.staleness_frames behind the stream head; "
                   "answers are stamped stale",
    "serve.tier": "a client requested an unknown quality tier; the "
                  "serve.default_tier renders instead",
    "session.sink": "a frame/tile sink or on_steer callback failed "
                    "max_sink_failures consecutive times and is "
                    "quarantined (disabled) for the rest of the run",
    "sim.fused_stencil": "fused Pallas stencil unavailable; XLA roll "
                         "formulation advances the sim",
    "stream.delta_resync": "a temporal-delta P/SKIP record arrived "
                           "without its base tile retained (an earlier "
                           "message was lost); dropped while waiting "
                           "for the next forced I-tile",
    "stream.gap": "VDI stream continuity: a sequence gap, duplicate/"
                  "reordered message, publisher restart, or a tile "
                  "frame abandoned incomplete past the assembler window",
    "stream.integrity": "a corrupt/truncated stream message failed "
                        "checksum/size/shape validation and was dropped "
                        "before decode",
    "stream.liveness": "a stream endpoint saw no traffic past "
                       "liveness_timeout_s and is reconnecting with "
                       "bounded exponential backoff",
    "stream.steering": "a malformed or oversized steering message was "
                       "dropped; the drain keeps going",
    "sim.stencil_schedule": "Mosaic rejected every probed stencil "
                            "schedule candidate for this grid/T",
    "topology.hier": "a hierarchical topology knob is inert on this "
                     "configuration (one host, or a mode with no "
                     "two-level composite); the flat single-level "
                     "path runs",
}


def ledger_registry() -> Dict[str, str]:
    """The static component catalog of the fallback ledger — every
    component a ``degrade()`` site in this repo can mint, with a one-line
    meaning. Cross-validated against the AST-discovered site list by
    sitpu-lint's round-trip test; see docs/STATIC_ANALYSIS.md."""
    return dict(_LEDGER_REGISTRY)


# The counter catalog — the static half of the counter contract,
# mirroring _LEDGER_REGISTRY for ``Recorder.count`` names. sitpu-lint's
# SITPU-COUNTER checker discovers the call sites by AST scan (string
# literals passed to ``.count(...)`` plus the string defaults/keyword
# literals of ``*_counter`` parameters, which parameterize the shared
# ring builders in parallel/pipeline.py) and tests/test_lint.py holds
# the two equal in both directions: a new ``rec.count("name")`` must
# register its name here, and a registry row without a live site must
# go. Keys are counter names, values say what one increment means.
_COUNTER_REGISTRY: Dict[str, str] = {
    "bricks_steps_built": "a brick-partition render step was compiled "
                          "for a (brick map, camera) combination",
    "build_steps": "the session (re)built its compiled render step set",
    "compile_scan_block": "a temporal scan frame-block was compiled",
    "compile_step": "one render/serve executable was compiled (lowered "
                    "+ jitted)",
    "dcn_bytes_received": "bytes received over the inter-host DCN seam "
                          "by the hierarchical composite",
    "dcn_bytes_sent": "bytes sent over the inter-host DCN seam by the "
                      "hierarchical composite",
    "dcn_hops_built": "one DCN ring hop of the hierarchical exchange "
                      "was built",
    "delivery_frames_delivered": "the async delivery worker finished "
                                 "one frame's sinks (tiles in column "
                                 "order, then the frame sinks)",
    "delivery_frames_enqueued": "the render loop handed one fetched "
                                "frame to the async delivery queue",
    "delivery_frames_inflight": "net frames inside the delivery plane "
                                "(+1 on enqueue, -1 on delivered or "
                                "shed) — a gauge expressed as a counter",
    "delivery_sheds": "the bounded delivery queue dropped its oldest "
                      "undelivered frame (overflow='drop_oldest')",
    "delta_bytes_saved": "wire bytes avoided by a temporal-delta "
                         "(SKIP/P) record vs the full I-tile encoding",
    "delta_march_skipped": "a rank's re-march was skipped because its "
                           "occupancy range signature was unchanged",
    "delta_tiles_skipped": "an unchanged tile shipped as a SKIP record",
    "flight_dumps": "the flight recorder dumped the last obs window "
                    "after an unhandled frame-loop exception",
    "frame_scan_builds": "a per-frame scan build was dispatched",
    "frames_abandoned": "the tile assembler abandoned a frame that "
                        "stayed incomplete past its window",
    "frames_eager_dispatch": "a frame went through the eager per-frame "
                             "dispatch path",
    "frames_scan_dispatch": "a frame was delivered from a compiled scan "
                            "block",
    "head_degraded_frames": "the head composited a frame with >= 1 rank "
                            "missing (degraded flag set)",
    "head_ranks_down": "head liveness marked a render rank silent",
    "head_ranks_readmitted": "a silent render rank resumed and was "
                             "readmitted to the composite",
    "hier_composite_builds": "a two-level hierarchical composite "
                             "schedule was built",
    "hier_plain_levels": "a plain (non-ring) allgather level of the "
                         "hierarchical exchange was built",
    "iframe_forced": "the delta encoder forced a full I-tile (resync or "
                     "cadence)",
    "ingest_stall_recoveries": "shm ingest saw a strictly-newer producer "
                               "frame again after a stall",
    "ingest_stalls": "shm ingest found no strictly-newer producer frame "
                     "past frame_timeout_ms",
    "obs_batch_drops": "a fleet-telemetry batch was dropped because the "
                       "collector socket would have blocked",
    "obs_batches_published": "a fleet-telemetry batch was handed to the "
                             "collector PUB socket",
    "occupancy_kbudget_builds": "a K-budget occupancy plan was built",
    "occupancy_pyramid_builds": "an occupancy pyramid was (re)built",
    "occupancy_ranges_builds": "a brick range-signature set was built",
    "profile_captures": "a ProfileCapture produced a phase attribution "
                        "(traced frames joined back to sitpu_* scopes)",
    "rebalance_replans": "a rebalance replan (slab or brick-steal) was "
                         "executed",
    "rebalance_steps_built": "a render step was compiled for a "
                             "rebalanced partition",
    "regime_switches": "the session switched between scan and eager "
                       "dispatch regimes",
    "reuse_steps_built": "a temporal-reuse render step (carried "
                         "fragments) was built",
    "ring_exchange_builds": "a ring all-to-all exchange program was "
                            "built",
    "ring_steps_built": "one hop of a ring exchange was built",
    "scan_blocks_dispatched": "a compiled scan block was dispatched",
    "scan_tail_eager_frames": "tail frames finished eagerly after a "
                              "partial scan block (count = frames)",
    "serve_answers": "the edge server sent one answer to a viewer",
    "serve_batch_cameras": "cameras rendered inside batched serve "
                           "dispatches (count = cameras)",
    "serve_batches": "the edge server ran one batched render dispatch",
    "serve_bytes_out": "bytes sent to viewers by the edge server",
    "serve_cache_hits": "a viewer camera hit the camera-delta cache",
    "serve_client_drops": "a malformed/oversized client message was "
                          "dropped by the serve loop",
    "serve_clients_evicted": "an idle viewer was evicted from the edge "
                             "server",
    "serve_frames_adopted": "the serve loop adopted a new VDI frame "
                            "from the stream",
    "serve_proxy_builds": "a planar-reprojection proxy renderer was "
                          "built",
    "serve_requests": "the edge server received one client camera "
                      "request",
    "serve_requests_coalesced": "duplicate per-frame camera requests "
                                "were coalesced into one render",
    "serve_sheds": "admission control refused a viewer or camera "
                   "request",
    "serve_stale_answers": "an answer was rendered from a VDI beyond "
                           "the staleness budget (stamped stale)",
    "sink_failures": "a frame/tile sink or steering callback raised",
    "sinks_quarantined": "a sink was disabled after repeated "
                         "consecutive failures",
    "slo_breaches": "the live SLO engine recorded one budget breach",
    "steering_drops": "a malformed steering message was dropped",
    "stream_drops": "a stream message was dropped (integrity or "
                    "continuity validation)",
    "stream_gap_messages": "a sequence gap/duplicate/reorder was "
                           "observed on a stream",
    "stream_reconnects": "a stream endpoint reconnected after a "
                         "liveness timeout",
    "tf_steps_reused": "a steered transfer function restored its cached "
                       "compiled steps",
    "tf_updates": "a steered transfer-function update was applied",
    "tiles_delivered": "the assembler delivered one complete tile",
    "wave_schedule_builds": "a tile-wave overlap schedule was built",
    "wave_steps_built": "a tile-wave render step was compiled",
    "wire_encode_builds": "a wire encode executable was built",
}


def counter_registry() -> Dict[str, str]:
    """The static name catalog of ``Recorder.count`` counters — every
    counter a call site in this repo can bump, with a one-line meaning.
    Cross-validated against the AST-discovered site list by sitpu-lint's
    SITPU-COUNTER round-trip test; see docs/OBSERVABILITY.md."""
    return dict(_COUNTER_REGISTRY)


def ledger() -> List[Dict[str, Any]]:
    """Snapshot of every degradation reported so far (insertion order)."""
    with _LEDGER_LOCK:
        return [dict(e) for e in _LEDGER.values()]


def clear_ledger() -> None:
    """Reset the process-global ledger (tests / bench child isolation)."""
    with _LEDGER_LOCK:
        _LEDGER.clear()


# ----------------------------------------------------------------- spans

class _Span:
    """One timed region. Always feeds the recorder's Timers (so the PR-1
    PhaseStats/windowed dumps are unchanged); records a structured event
    only when the recorder is enabled."""

    __slots__ = ("rec", "name", "frame", "attrs", "t0", "depth", "parent")

    def __init__(self, rec: "Recorder", name: str,
                 frame: Optional[int], attrs: Optional[dict]):
        self.rec = rec
        self.name = name
        self.frame = frame
        self.attrs = attrs

    def __enter__(self):
        rec = self.rec
        if rec.enabled:
            stack = rec._stack
            self.depth = len(stack)
            self.parent = stack[-1] if stack else None
            stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        rec = self.rec
        dt = t1 - self.t0
        rec.timers.record(self.name, dt)
        if rec.enabled:
            rec._stack.pop()
            ev = {"type": "span", "name": self.name,
                  "rank": rec.rank,
                  "ts": self.t0 - rec.epoch, "dur": dt,
                  "depth": self.depth}
            if self.parent is not None:
                ev["parent"] = self.parent
            if self.frame is not None:
                ev["frame"] = self.frame
            if self.attrs:
                ev["attrs"] = self.attrs
            rec._push(ev)
        return False


class Recorder:
    """Per-run telemetry recorder. ``enabled=False`` is the hot-path
    no-op configuration: spans delegate to the Timers only, ``events``
    stays empty forever and ``flush()`` writes nothing."""

    def __init__(self, enabled: bool = True, rank: int = 0,
                 window: int = 100, log=None,
                 trace_path: Optional[str] = None,
                 metrics_path: Optional[str] = None,
                 timers: Optional[Timers] = None,
                 max_events: int = 500_000):
        self.enabled = enabled
        self.rank = rank
        self.timers = timers if timers is not None else Timers(
            window=window, log=log, rank=rank)
        self.trace_path = trace_path or None
        self.metrics_path = metrics_path or None
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()
        self.events: List[dict] = []
        self.counters: Dict[str, float] = {}
        self.max_events = max_events
        # spans now open/close on the delivery worker threads too
        # (runtime/delivery.py): the open-span stack is per-thread so a
        # worker span cannot corrupt the loop thread's nesting, and the
        # counter read-modify-write is locked
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._dropped = 0

    @property
    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    @classmethod
    def from_config(cls, obs_cfg, rank: int = 0, log=None,
                    window: Optional[int] = None) -> "Recorder":
        """Build from a ``config.ObsConfig`` block (``obs.window == 0``
        inherits the caller's window, normally runtime.stats_window)."""
        return cls(enabled=obs_cfg.enabled, rank=rank, log=log,
                   window=obs_cfg.window or window or 100,
                   trace_path=obs_cfg.trace_path,
                   metrics_path=obs_cfg.metrics_path)

    # ------------------------------------------------------------ record
    def span(self, name: str, frame: Optional[int] = None,
             **attrs) -> _Span:
        """Context manager timing one phase; ``frame``/``attrs`` become
        event attribution. Usable whether enabled or not."""
        return _Span(self, name, frame, attrs or None)

    def count(self, name: str, n: float = 1) -> None:
        """Bump a named counter (compile events, scan blocks, eager
        frames, ...). O(1) dict update — cheap enough to leave in hot
        paths unconditionally; the counter event stream is only recorded
        when enabled."""
        with self._lock:
            value = self.counters[name] = self.counters.get(name, 0) + n
        if self.enabled:
            self._push({"type": "counter", "name": name, "rank": self.rank,
                        "ts": time.perf_counter() - self.epoch,
                        "value": value})

    def event(self, name: str, frame: Optional[int] = None,
              **attrs) -> None:
        """Instant event (no duration)."""
        if not self.enabled:
            return
        ev = {"type": "instant", "name": name, "rank": self.rank,
              "ts": time.perf_counter() - self.epoch}
        if frame is not None:
            ev["frame"] = frame
        if attrs:
            ev["attrs"] = attrs
        self._push(ev)

    def _push(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            self._dropped += 1     # bound memory over long campaigns
            return
        self.events.append(ev)

    def frame_done(self) -> None:
        self.timers.frame_done()

    # ----------------------------------------------------------- summary
    def summary(self) -> dict:
        """One JSON-able record of the run: per-phase stats, counters and
        the process-global fallback ledger."""
        phases = {name: {"avg_ms": round(st.avg * 1e3, 3),
                         "total_s": round(st.total, 4), "n": st.n}
                  for name, st in sorted(self.timers.stats.items())}
        return {"rank": self.rank, "frames": self.timers.frames,
                "enabled": self.enabled, "phases": phases,
                "counters": dict(self.counters),
                "events_recorded": len(self.events),
                "events_dropped": self._dropped,
                "degradations": ledger()}

    # --------------------------------------------------------- exporters
    def chrome_trace_events(self) -> List[dict]:
        """Chrome-trace / Perfetto event list: spans as complete ("X")
        events, counters as "C", instants as "i", plus process-name
        metadata. ``pid`` is the rank, timestamps in µs from the
        recorder epoch."""
        out = [{"ph": "M", "name": "process_name", "pid": self.rank,
                "tid": 0,
                "args": {"name": f"rank {self.rank}"}}]
        for ev in self.events:
            ts = round(ev["ts"] * 1e6, 1)
            base = {"name": ev["name"], "pid": ev.get("rank", self.rank),
                    "tid": 0, "ts": ts}
            args = dict(ev.get("attrs") or {})
            if "frame" in ev:
                args["frame"] = ev["frame"]
            if ev["type"] == "span":
                base.update(ph="X", dur=round(ev["dur"] * 1e6, 1),
                            cat="phase")
                if "parent" in ev:
                    args["parent"] = ev["parent"]
            elif ev["type"] == "counter":
                base.update(ph="C", cat="counter")
                args = {"value": ev["value"]}
            else:
                base.update(ph="i", s="p", cat="event")
            base["args"] = args
            out.append(base)
        for entry in ledger():
            out.append({"ph": "i", "s": "g", "name":
                        f"degrade:{entry['component']}", "pid": self.rank,
                        "tid": 0, "ts": 0.0, "cat": "degrade",
                        "args": entry})
        return out

    def export_chrome_trace(self, path: str) -> str:
        """Write ``trace.json`` (open in ui.perfetto.dev or
        chrome://tracing)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_trace_events(),
                       "displayTimeUnit": "ms",
                       "otherData": {"rank": self.rank,
                                     "epoch_unix": self.epoch_unix}}, f)
        return path

    def export_metrics_jsonl(self, path: str) -> str:
        """Write the raw event stream as JSON lines, one event per line,
        terminated by one ``summary`` line (the grep/jq-friendly twin of
        the trace file)."""
        with open(path, "w") as f:
            for ev in self.events:
                f.write(json.dumps(ev) + "\n")
            f.write(json.dumps({"type": "summary", **self.summary()})
                    + "\n")
        return path

    def flush(self) -> None:
        """Write the configured sinks (no-op when disabled or pathless).
        Idempotent — call at end of run(), or repeatedly mid-campaign for
        a monotonically growing snapshot."""
        if not self.enabled:
            return
        if self.trace_path:
            self.export_chrome_trace(self.trace_path)
        if self.metrics_path:
            self.export_metrics_jsonl(self.metrics_path)


# ------------------------------------------------------ flight recorder

_FLIGHT_REASON = ("unhandled exception tore down the frame loop; the "
                  "last obs window was dumped best-effort to the "
                  "configured paths")


def flight_flush(rec: Optional[Recorder] = None,
                 where: str = "run") -> bool:
    """Crash-path dump: write whatever the recorder holds to its
    configured sinks, best-effort, so an exception mid-run does not lose
    the final unflushed window (the one that usually explains the
    crash). Never raises — this runs while the original exception is
    propagating, and a broken disk must not mask it. Returns True when
    a dump was attempted (enabled recorder with >= 1 sink path)."""
    rec = rec or get_recorder()
    if not rec.enabled or not (rec.trace_path or rec.metrics_path):
        return False
    rec.count("flight_dumps")
    rec.event("flight_dump", where=where)
    degrade("obs.flight_recorder", where, "crash_flush", _FLIGHT_REASON,
            warn=False)
    try:
        rec.flush()
    except Exception:
        pass    # the in-flight exception is the story, not this one
    return True


# ------------------------------------------------------- global recorder

_GLOBAL = Recorder(enabled=False)


def get_recorder() -> Recorder:
    """The process's active recorder (a disabled one until a session or
    harness installs its own)."""
    return _GLOBAL


def set_recorder(rec: Recorder) -> Recorder:
    """Install ``rec`` as the active recorder; returns the previous one
    so callers can restore it."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = rec
    return prev
