"""Device-side enrichment: what the compiled executable says about
itself. Host spans time the wall clock; XLA's ``cost_analysis()`` of the
compiled step says how many HBM bytes and FLOPs the frame moves — the
two together make a BENCH delta attributable (compute-bound vs
bandwidth-bound vs dispatch-bound) without xprof archaeology.

Lifted out of ``bench.py`` so the session, the bench harness and the
phase diagnostics all read the same snapshot shape.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def cost_snapshot(jitted, *args) -> Optional[Dict[str, Any]]:
    """XLA cost-analysis snapshot of ``jitted(*args)``: ``bytes_accessed``
    (operand + output + scheduled HLO intermediate traffic), ``flops``
    and ``transcendentals`` when the backend reports them. Returns None
    when the backend's analysis is empty/absent, and an
    ``{"source": "unavailable", "error": ...}`` record when lowering or
    compilation raises — callers wanting a traffic-model fallback should
    branch on ``snap is None or "bytes_accessed" not in snap``.

    Lowering hits the jit/persistent compile cache, so calling this after
    the warmup frame costs no fresh compilation."""
    try:
        ca = jitted.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not ca:
            return None
        snap = {"source": "xla_cost_analysis"}
        for key, out in (("bytes accessed", "bytes_accessed"),
                         ("flops", "flops"),
                         ("transcendentals", "transcendentals")):
            v = ca.get(key)
            if v is not None and float(v) > 0:
                snap[out] = float(v)
        return snap if len(snap) > 1 else None
    except Exception as e:                     # noqa: BLE001 — best-effort
        return {"source": "unavailable",
                "error": f"{type(e).__name__}: {str(e)[:120]}"}


def device_cost(jitted, *args) -> Dict[str, Any]:
    """The ONE cost-analysis join every consumer reads (bench.py,
    benchmarks/phase_bench.py, obs/roofline.py, the divergence engine):
    ``cost_snapshot`` normalized to an always-a-dict record with
    identical keys everywhere, and the unavailable case minted through
    the ``bench.cost_analysis`` degrade component so artifacts carry WHY
    the bytes/flops are missing.

    Returns ``{"source": "xla_cost_analysis", "bytes_accessed": ...,
    "flops": ..., ...}`` on success; ``{"source": "unavailable",
    "error": ...}`` (degrade minted) otherwise."""
    from scenery_insitu_tpu.obs.recorder import degrade

    snap = cost_snapshot(jitted, *args)
    if snap is None or "bytes_accessed" not in snap:
        err = (snap or {}).get("error", "no cost analysis")
        degrade("bench.cost_analysis", "xla_cost_analysis",
                "traffic_model",
                f"backend reported no cost analysis ({err})", warn=False)
        return {"source": "unavailable", "error": err}
    return snap
