"""Seeded, deterministic fault injection for the delivery plane
(docs/ROBUSTNESS.md "Chaos harness").

Every seam where bytes cross a failure domain — the zmq VDI/steering
streams, the UDP video stream, the shm ingest ring — gets an injector
that perturbs the SEND side, so the receive-side hardening
(runtime/streaming.py integrity validation, runtime/head.py rank
liveness, ingest/shm.py stall supervision) can be exercised in tier-1
without real network flakes:

- ``ChaosSocket`` wraps a zmq/UDP send socket behind a ``FaultSpec``
  (drop, corrupt bytes, truncate multipart, reorder, duplicate, delay),
  driven by one seeded ``random.Random`` — same seed, same faults,
  every run.
- ``SilentRank`` wraps a ``RankImageSender`` that goes silent after N
  frames (the dead-render-rank scenario for ``HeadNode``).
- ``kill_producer`` ends an external shm producer process (the
  dead-simulation scenario for ``ShmVolumeSource``).
- ``run_matrix`` executes the whole injector × endpoint matrix
  in-process and returns a machine-readable chaos report (the CI
  artifact): every scenario must end with the endpoint alive, the
  expected ledger component minted, and zero unhandled exceptions.

``python -m scenery_insitu_tpu.testing.faults --seed 7 --out
chaos_report.json`` writes the report and exits non-zero if any
scenario failed.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_KINDS = ("drop", "corrupt", "truncate", "reorder", "duplicate",
               "delay")


@dataclass(frozen=True)
class FaultSpec:
    """Per-message fault probabilities (0..1) for one chaos run. All
    zero = transparent passthrough (the clean-path parity control)."""

    drop: float = 0.0       # message vanishes
    corrupt: float = 0.0    # bytes flipped in the payload blob
    truncate: float = 0.0   # last part of a multipart message removed
    reorder: float = 0.0    # message held and sent after its successor
    duplicate: float = 0.0  # message sent twice
    delay: float = 0.0      # message sent late (sleep delay_s first)
    delay_s: float = 0.002
    corrupt_bytes: int = 8  # how many byte positions each corruption flips


@dataclass
class FaultReport:
    """What the injector actually did — seeded, so a failing test can be
    replayed exactly."""

    seed: int = 0
    injected: Dict[str, int] = field(default_factory=dict)
    sent: int = 0

    def record(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def to_dict(self) -> dict:
        return {"seed": self.seed, "sent": self.sent,
                "injected": dict(self.injected)}


class ChaosSocket:
    """Wraps the SEND side of a zmq socket (``send`` /
    ``send_multipart``) or a UDP socket (``sendto``); every outgoing
    message rolls against the ``FaultSpec`` with the seeded RNG. The
    receive side and every other attribute pass through untouched, so
    ``endpoint.sock = ChaosSocket(endpoint.sock, spec, seed)`` (or the
    ``inject`` helper) is the whole integration."""

    def __init__(self, sock, spec: FaultSpec, seed: int = 0,
                 report: Optional[FaultReport] = None):
        self.sock = sock
        self.spec = spec
        self.rng = random.Random(seed)
        self.report = report if report is not None else FaultReport(seed)
        self._held = None         # (send_fn_name, msg, extra) reorder slot

    # --------------------------------------------------- send interface
    def send(self, data, *args, **kw):
        self._dispatch("send", data, args)

    def send_multipart(self, parts, *args, **kw):
        self._dispatch("send_multipart", list(parts), args)

    def sendto(self, data, *addr):
        self._dispatch("sendto", data, addr)
        return len(data)          # socket.sendto contract: bytes "sent"

    def close(self, *args, **kw):
        self.flush()
        return self.sock.close(*args, **kw)

    def flush(self) -> None:
        """Release a held (reordered) message; call at end of a drill so
        the last message is never lost to the reorder buffer."""
        if self._held is not None:
            name, msg, extra = self._held
            self._held = None
            getattr(self.sock, name)(msg, *extra)

    def __getattr__(self, name):
        return getattr(self.sock, name)

    # ----------------------------------------------------------- faults
    def _dispatch(self, name, msg, extra) -> None:
        spec, rng = self.spec, self.rng
        self.report.sent += 1
        if spec.delay and rng.random() < spec.delay:
            self.report.record("delay")
            time.sleep(spec.delay_s)
        if spec.drop and rng.random() < spec.drop:
            self.report.record("drop")
            self.flush()          # the held predecessor still goes out
            return
        if spec.corrupt and rng.random() < spec.corrupt:
            msg = self._corrupt(name, msg)
            self.report.record("corrupt")
        if name == "send_multipart" and len(msg) > 1 \
                and spec.truncate and rng.random() < spec.truncate:
            msg = msg[:-1]
            self.report.record("truncate")
        if spec.reorder and self._held is None \
                and rng.random() < spec.reorder:
            self._held = (name, msg, extra)
            self.report.record("reorder")
            return
        getattr(self.sock, name)(msg, *extra)
        if spec.duplicate and rng.random() < spec.duplicate:
            self.report.record("duplicate")
            getattr(self.sock, name)(msg, *extra)
        self.flush()              # held message follows its successor

    def _corrupt(self, name, msg):
        """Flip ``corrupt_bytes`` seeded byte positions in the payload —
        the LAST part of a multipart message (a compressed blob), the
        whole datagram/message otherwise."""
        rng = self.rng
        target = bytearray(msg[-1] if name == "send_multipart" else msg)
        for _ in range(self.spec.corrupt_bytes):
            if not target:
                break
            target[rng.randrange(len(target))] ^= 0xFF
        if name == "send_multipart":
            return list(msg[:-1]) + [bytes(target)]
        return bytes(target)


def inject(endpoint, spec: FaultSpec, seed: int = 0) -> FaultReport:
    """Swap ``endpoint.sock`` (VDIPublisher, SteeringPublisher,
    RankImageSender, VideoStreamer ...) for a ChaosSocket; returns the
    FaultReport the injector will fill."""
    chaos = ChaosSocket(endpoint.sock, spec, seed)
    endpoint.sock = chaos
    return chaos.report


class SilentRank:
    """Wrap a ``RankImageSender``: frames below ``after`` pass through,
    later ones are swallowed — the silent-rank scenario for HeadNode's
    per-rank liveness. ``resume_at`` (optional) lets the rank come back
    so re-admission can be exercised."""

    def __init__(self, sender, after: int,
                 resume_at: Optional[int] = None):
        self.sender = sender
        self.after = after
        self.resume_at = resume_at
        self.swallowed = 0

    def send(self, frame: int, image, depth) -> None:
        silent = frame >= self.after and (self.resume_at is None
                                          or frame < self.resume_at)
        if silent:
            self.swallowed += 1
            return
        self.sender.send(frame, image, depth)

    def close(self) -> None:
        self.sender.close()


def kill_producer(proc, timeout_s: float = 5.0) -> int:
    """End an external shm producer process (the kill-the-producer
    scenario for ShmVolumeSource's stall supervision); returns the exit
    code."""
    proc.kill()
    return proc.wait(timeout=timeout_s)


# ---------------------------------------------------------- chaos matrix

def _pump_stream(pub, sub, vdi, meta, frames: int, seed: int):
    """Publish ``frames`` frames through whatever chaos wraps ``pub``
    and drain the subscriber; returns (received tuples, drop records)."""
    import numpy as np

    from scenery_insitu_tpu.runtime.streaming import StreamDrop

    received, drops = [], []
    for i in range(frames):
        pub.publish(vdi, meta._replace(index=np.int32(i)))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        got = sub.receive_tile(timeout_ms=100)
        if got is None:
            break
        if isinstance(got, StreamDrop):
            drops.append(got)
        else:
            received.append(got)
    return received, drops


def run_matrix(seed: int = 0, frames: int = 12) -> dict:
    """The seeded injector × endpoint chaos matrix, in one process.

    Each scenario builds a fresh publisher/subscriber pair (ephemeral
    ports), injects one fault kind at a deterministic rate, runs the
    stream, and records: endpoint alive (no unhandled exception), the
    ledger components minted, and the injector/validator tallies. The
    returned report is the CI chaos artifact."""
    import numpy as np

    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FaultConfig
    from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
    from scenery_insitu_tpu.runtime.streaming import (FrameAssembler,
                                                      SteeringEndpoint,
                                                      SteeringPublisher,
                                                      VDIPublisher,
                                                      VDISubscriber)

    rng = np.random.default_rng(seed)
    K, H, W = 4, 12, 16
    vdi = VDI(rng.random((K, 4, H, W)).astype(np.float32),
              rng.random((K, 2, H, W)).astype(np.float32))
    meta = VDIMetadata.create(np.eye(4), np.eye(4),
                              volume_dims=(8, 8, 8), window_dims=(W, H),
                              nw=0.1, index=0)
    scenarios: List[dict] = []

    def scenario(name: str, expect_components, fn) -> None:
        obs.clear_ledger()
        entry = {"scenario": name, "alive": True,
                 "expected_components": sorted(expect_components)}
        try:
            entry.update(fn() or {})
        except Exception as e:   # sitpu-lint: disable=SITPU-LEDGER
            # reporting-only capture: an exception here IS the chaos
            # verdict ("endpoint died"), recorded in the artifact — the
            # run itself must keep going to finish the matrix
            entry["alive"] = False
            entry["error"] = repr(e)
        minted = {e["component"] for e in obs.ledger()}
        entry["ledger_components"] = sorted(minted)
        entry["ok"] = entry["alive"] and \
            set(expect_components) <= minted
        scenarios.append(entry)

    def stream_drill(kind: str, expect, **spec_kw):
        def fn():
            pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
            sub = VDISubscriber(pub.endpoint)
            try:
                time.sleep(0.2)
                report = inject(pub, FaultSpec(**spec_kw), seed)
                received, drops = _pump_stream(pub, sub, vdi, meta,
                                               frames, seed)
                # whatever survived must decode exactly (integrity means
                # corrupt frames NEVER decode wrong — they drop)
                for r, _, _ in received:
                    assert np.isfinite(np.asarray(r.color)).all()
                return {"injected": report.to_dict(),
                        "frames_received": len(received),
                        "drops": len(drops),
                        "subscriber_stats": dict(sub.stats)}
            finally:
                pub.close()
                sub.close()
        scenario(f"vdi_stream/{kind}", expect, fn)

    # --- VDI stream × every byte-level injector -------------------------
    stream_drill("drop", ["stream.gap"], drop=0.5)
    stream_drill("corrupt", ["stream.integrity"], corrupt=0.7)
    stream_drill("truncate", ["stream.integrity"], truncate=0.7)
    stream_drill("reorder", ["stream.gap"], reorder=0.9)
    stream_drill("duplicate", ["stream.gap"], duplicate=1.0)
    stream_drill("delay", [], delay=1.0, delay_s=0.001)

    # --- clean-path parity control --------------------------------------
    def clean():
        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        sub = VDISubscriber(pub.endpoint)
        try:
            time.sleep(0.2)
            received, drops = _pump_stream(pub, sub, vdi, meta, 4, seed)
            assert drops == [] and len(received) == 4
            for r, _, _ in received:
                np.testing.assert_array_equal(np.asarray(vdi.color),
                                              r.color)
            hdr = pub.last_bytes["header"]
            raw = (np.asarray(vdi.color).nbytes
                   + np.asarray(vdi.depth).nbytes)
            return {"frames_received": len(received),
                    "header_bytes": hdr, "frame_bytes": raw,
                    "header_overhead": round(hdr / raw, 4)}
        finally:
            pub.close()
            sub.close()
    scenario("vdi_stream/clean_parity", [], clean)

    # --- tile stream + assembler under tile loss ------------------------
    def tiles():
        from scenery_insitu_tpu.runtime.streaming import StreamDrop

        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        sub = VDISubscriber(pub.endpoint)
        try:
            time.sleep(0.2)
            report = inject(pub, FaultSpec(drop=0.3), seed)
            asm = FrameAssembler(window=2)
            ntiles, wb = 4, W // 4
            for f in range(frames):
                for t in range(ntiles):
                    tv = VDI(np.asarray(vdi.color)[..., t * wb:(t + 1) * wb],
                             np.asarray(vdi.depth)[..., t * wb:(t + 1) * wb])
                    pub.publish_tile(
                        tv, meta._replace(index=np.int32(f)),
                        t, ntiles, t * wb)
            done = []
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                got = sub.receive_tile(timeout_ms=100)
                if got is None:
                    break
                if isinstance(got, StreamDrop):
                    continue
                out = asm.add(*got)
                if out is not None:
                    done.append(out)
            for v, _ in done:     # complete frames must be bit-exact
                np.testing.assert_array_equal(np.asarray(vdi.color),
                                              v.color)
            assert asm.stats["abandoned"] > 0
            return {"injected": report.to_dict(),
                    "frames_assembled": len(done),
                    "assembler_stats": dict(asm.stats)}
        finally:
            pub.close()
            sub.close()
    scenario("tile_stream/drop_assembler", ["stream.gap"], tiles)

    # --- steering endpoint under garbage --------------------------------
    def steering():
        ep = SteeringEndpoint("tcp://127.0.0.1:0",
                              fault=FaultConfig(max_message_bytes=4096))
        viewer = SteeringPublisher(ep.endpoint)
        try:
            time.sleep(0.2)
            good = []
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not good:
                viewer.sock.send(b"\xc1\x00\xff garbage not msgpack")
                viewer.sock.send(b"\x00" * 8192)       # oversized
                viewer.send({"type": "camera", "eye": [0, 0, 9]})
                time.sleep(0.02)
                good.extend(ep.drain())
            assert good and good[-1]["type"] == "camera"
            assert ep.stats["dropped"] > 0
            return {"drained": len(good),
                    "endpoint_stats": dict(ep.stats)}
        finally:
            viewer.close()
            ep.close()
    scenario("steering/malformed_oversized", ["stream.steering"],
             steering)

    # --- serving tier (ISSUE 13): churn, backpressure, delta mid-join ---
    def _serve_fixture():
        """A tiny REAL slice-march VDI (the matrix's synthetic identity
        matrices are not a renderable camera) + a loopback server."""
        from scenery_insitu_tpu.config import (FrameworkConfig,
                                               SliceMarchConfig,
                                               VDIConfig)
        from scenery_insitu_tpu.core.camera import Camera
        from scenery_insitu_tpu.core.transfer import for_dataset
        from scenery_insitu_tpu.core.volume import procedural_volume
        from scenery_insitu_tpu.ops import slicer

        vol = procedural_volume(16, kind="blobs", seed=seed)
        cam0 = Camera.create((0.1, 0.3, 2.8), fov_y_deg=45.0, near=0.3,
                             far=10.0)
        spec = slicer.make_spec(
            cam0, vol.data.shape, SliceMarchConfig(matmul_dtype="f32"))
        svdi, smeta, _ = slicer.generate_vdi_mxu(
            vol, for_dataset("procedural"), cam0, spec,
            VDIConfig(max_supersegments=4, adaptive_iters=1))
        cfg = FrameworkConfig().with_overrides(
            "serve.width=24", "serve.height=20", "serve.num_slices=8",
            "serve.batch_size=4", "serve.buckets=[1,2,4]")
        return svdi, smeta, cam0, cfg

    def _pump_serve(srv, clients, secs):
        import time as _t

        from scenery_insitu_tpu.serve import ViewerFrame

        deadline = _t.monotonic() + secs
        answers = 0
        while _t.monotonic() < deadline:
            srv.run_once(timeout_ms=10)
            for c in clients:
                got = c.poll(timeout_ms=0)
                if isinstance(got, ViewerFrame):
                    answers += 1
        return answers

    def serve_churn():
        """Clients joining and leaving MID-FRAME while the server
        answers: admissions beyond max_viewers shed typed, leavers are
        forgotten, the server never raises."""
        from scenery_insitu_tpu.core.camera import orbit
        from scenery_insitu_tpu.runtime.streaming import VDIPublisher
        from scenery_insitu_tpu.serve import ViewerClient, ViewerServer

        svdi, smeta, cam0, cfg = _serve_fixture()
        cfg = cfg.with_overrides("serve.max_viewers=2")
        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        srv = ViewerServer(cfg, connect=pub.endpoint,
                           bind="tcp://127.0.0.1:0")
        churned = []
        try:
            time.sleep(0.2)
            pub.publish(svdi, smeta)
            deadline = time.monotonic() + 20
            while srv.frame is None and time.monotonic() < deadline:
                srv.pump_stream(timeout_ms=100)
            assert srv.frame is not None
            answers = 0
            for round_ in range(3):
                batch = [ViewerClient(srv.endpoint, tier="proxy")
                         for _ in range(4)]        # 4 > max_viewers=2
                churned.extend(batch)
                for i, c in enumerate(batch):
                    c.request(orbit(cam0, 0.05 * i + 0.02 * round_))
                answers += _pump_serve(srv, batch, 1.0)
                for c in batch[:2]:                # leavers mid-stream
                    c.bye()
                srv.pump_clients()
            assert answers > 0
            assert srv.stats["sheds"] > 0
            return {"answers": answers, "server_stats": dict(srv.stats)}
        finally:
            for c in churned:
                c.close()
            srv.close()
            pub.close()
    scenario("serve/client_churn", ["serve.shed"], serve_churn)

    def serve_backpressure():
        """A slow/flooding client vs admission control: its own requests
        coalesce latest-wins, and distinct clients beyond queue_cap shed
        typed — the serve loop never blocks on the slow consumer."""
        from scenery_insitu_tpu.core.camera import orbit
        from scenery_insitu_tpu.runtime.streaming import VDIPublisher
        from scenery_insitu_tpu.serve import (ServeDrop, ViewerClient,
                                              ViewerServer)

        svdi, smeta, cam0, cfg = _serve_fixture()
        cfg = cfg.with_overrides("serve.max_viewers=4",
                                 "serve.queue_cap=1")
        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        srv = ViewerServer(cfg, connect=pub.endpoint,
                           bind="tcp://127.0.0.1:0")
        flooder = ViewerClient(srv.endpoint, tier="proxy")
        other = ViewerClient(srv.endpoint, tier="proxy")
        try:
            time.sleep(0.2)
            pub.publish(svdi, smeta)
            deadline = time.monotonic() + 20
            while srv.frame is None and time.monotonic() < deadline:
                srv.pump_stream(timeout_ms=100)
            # the flooder never reads; its burst coalesces to one slot
            for i in range(6):
                flooder.request(orbit(cam0, 0.03 * i))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not srv.queue:
                srv.pump_clients()
                time.sleep(0.01)
            srv.pump_clients()
            assert len(srv.queue) <= 1
            # a second client against the full queue: typed shed
            other.request(orbit(cam0, 0.5))
            shed = None
            deadline = time.monotonic() + 10
            while shed is None and time.monotonic() < deadline:
                srv.pump_clients()
                got = other.poll(timeout_ms=10)
                if isinstance(got, ServeDrop) and got.kind == "shed":
                    shed = got
            assert shed is not None and shed.reason == "queue_cap"
            return {"server_stats": dict(srv.stats),
                    "coalesced": srv.stats["coalesced"]}
        finally:
            flooder.close()
            other.close()
            srv.close()
            pub.close()
    scenario("serve/slow_client_backpressure", ["serve.shed"],
             serve_backpressure)

    def serve_delta_midjoin():
        """The serve subscriber joins a temporal-delta stream
        mid-flight: P/SKIP records before the first I-frame are typed
        resync drops, and the server is whole within iframe_period."""
        from scenery_insitu_tpu.config import DeltaConfig
        from scenery_insitu_tpu.runtime.streaming import VDIPublisher
        from scenery_insitu_tpu.serve import ViewerServer

        svdi, smeta, cam0, cfg = _serve_fixture()
        # iframe_period is generous so MANY P records precede the forced
        # I: the subscriber's SUB join settles while P-frames flow, and
        # the scenario's resync-drop assertion never races the first
        # I-frame on a loaded runner
        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                           precision="qpack8", epoch=seed + 1,
                           delta=DeltaConfig(enabled=True,
                                             iframe_period=16))
        # the stream is already past its first I-frame when we join
        pub.publish(svdi, smeta._replace(index=np.int32(0)))
        pub.publish(svdi, smeta._replace(index=np.int32(1)))
        srv = ViewerServer(cfg, connect=pub.endpoint,
                           bind="tcp://127.0.0.1:0")
        try:
            time.sleep(0.2)
            deadline = time.monotonic() + 20
            i = 2
            while srv.frame is None and time.monotonic() < deadline:
                pub.publish(svdi, smeta._replace(index=np.int32(i)))
                i += 1
                srv.pump_stream(timeout_ms=300)
            assert srv.frame is not None, "never recovered on an I-frame"
            assert srv.stats["stream_drops"] > 0    # the resync waits
            return {"frames_published": i,
                    "server_stats": dict(srv.stats),
                    "subscriber_stats": dict(srv.sub.stats)}
        finally:
            srv.close()
            pub.close()
    scenario("serve/delta_resync_midjoin", ["stream.delta_resync"],
             serve_delta_midjoin)

    # --- async delivery plane under a slow sink (ISSUE 19) --------------
    def delivery_backpressure():
        """A deliberately slow frame sink behind the bounded delivery
        queue in ``drop_oldest`` mode: the submitting loop never blocks
        on the sink, the stalest undelivered frames shed typed
        (``delivery.shed`` ledger + ``delivery_sheds`` counter), the
        survivors arrive strictly FIFO, and drain() leaves nothing in
        flight."""
        import threading

        from scenery_insitu_tpu.config import DeliveryConfig
        from scenery_insitu_tpu.runtime.delivery import DeliveryExecutor
        from scenery_insitu_tpu.runtime.failsafe import SinkGuard

        sink_s = 0.05
        done, lock = [], threading.Lock()

        def slow_sink(index, payload):
            time.sleep(sink_s)
            with lock:
                done.append(index)

        cfg = DeliveryConfig(enabled=True, queue_frames=2,
                             overflow="drop_oldest")
        ex = DeliveryExecutor(cfg, SinkGuard(), [], [slow_sink])
        try:
            t0 = time.monotonic()
            for i in range(frames):
                ex.submit(i, {"frame": i})
            submit_s = time.monotonic() - t0
            # the loop thread must never serialize on the slow sink
            assert submit_s < 0.5 * frames * sink_s, submit_s
            assert ex.drain(timeout_s=30.0)
        finally:
            ex.close()
        with lock:
            got = list(done)
        assert got == sorted(got) and len(set(got)) == len(got)
        assert ex.sheds > 0 and ex.delivered == len(got)
        assert ex.delivered + ex.sheds == ex.enqueued
        return {"submitted": frames, "delivered": ex.delivered,
                "sheds": ex.sheds, "submit_s": round(submit_s, 4)}
    scenario("delivery/slow_sink_backpressure", ["delivery.shed"],
             delivery_backpressure)

    # --- telemetry collector dies mid-run (ISSUE 17) --------------------
    def collector_death():
        """The fleet-telemetry collector is killed halfway through the
        run: every frame still crosses the delivery plane (telemetry is
        a side-channel, never on the frame path), and the presumed-lost
        batches are counted and ledgered ``obs.collector``."""
        from scenery_insitu_tpu.obs.collector import (Collector,
                                                      ObsPublisher)
        from scenery_insitu_tpu.runtime.streaming import (StreamDrop,
                                                          VDIPublisher,
                                                          VDISubscriber)

        saved_rec = obs.get_recorder()
        rec = obs.Recorder(enabled=True)
        obs.set_recorder(rec)
        col = Collector()
        opub = ObsPublisher(col.endpoint, col.hb_endpoint, rank=0,
                            interval_s=0.0)
        pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib")
        sub = VDISubscriber(pub.endpoint)
        killed_at = frames // 2
        try:
            time.sleep(0.2)
            alive_batches = 0
            for i in range(frames):
                if i == killed_at:
                    col.close()          # mid-run, no goodbye
                pub.publish(vdi, meta._replace(index=np.int32(i)))
                opub.pump(rec, force=True)
                if i < killed_at:
                    alive_batches += col.poll(20)
            received = []
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                got = sub.receive_tile(timeout_ms=100)
                if got is None:
                    break
                if not isinstance(got, StreamDrop):
                    received.append(got)
            # the delivery plane never noticed: EVERY frame arrived
            assert len(received) == frames, \
                f"delivery impacted: {len(received)}/{frames}"
            assert alive_batches > 0          # telemetry flowed before
            assert opub.drops > 0             # ...and was ledgered after
            assert rec.counters.get("obs_batch_drops", 0) > 0
            return {"frames_received": len(received),
                    "batches_before_kill": alive_batches,
                    "publisher": {"batches": opub.batches,
                                  "drops": opub.drops}}
        finally:
            obs.set_recorder(saved_rec)
            opub.close()
            pub.close()
            sub.close()
    scenario("obs/collector_death_midrun", ["obs.collector"],
             collector_death)

    # --- subscriber liveness reconnect ----------------------------------
    def liveness():
        sub = VDISubscriber(
            "tcp://127.0.0.1:1",     # nothing listens: pure silence
            fault=FaultConfig(liveness_timeout_s=0.05,
                              backoff_base_s=0.01, backoff_cap_s=0.05))
        try:
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline \
                    and sub.stats["reconnects"] < 2:
                sub.receive(timeout_ms=30)
            assert sub.stats["reconnects"] >= 2
            return {"subscriber_stats": dict(sub.stats)}
        finally:
            sub.close()
    scenario("vdi_stream/liveness_reconnect", ["stream.liveness"],
             liveness)

    report = {
        "seed": seed,
        "frames_per_scenario": frames,
        "scenarios": scenarios,
        "ok": all(s["ok"] for s in scenarios),
    }
    return report


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        description="seeded delivery-plane chaos matrix "
                    "(docs/ROBUSTNESS.md)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--frames", type=int, default=12)
    ap.add_argument("--out", default=None, help="chaos report JSON path")
    args = ap.parse_args(argv)
    report = run_matrix(seed=args.seed, frames=args.frames)
    blob = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    print(blob if not args.out else
          f"chaos matrix {'OK' if report['ok'] else 'FAILED'}: "
          f"{sum(s['ok'] for s in report['scenarios'])}/"
          f"{len(report['scenarios'])} scenarios -> {args.out}",
          file=sys.stdout, flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
