"""Test-support subpackage: seeded fault injection for the delivery
plane (``testing.faults``; docs/ROBUSTNESS.md). Importable from
production code for chaos drills but never imported BY it."""
