"""Subprocess multi-process harness — real ``jax.distributed`` fleets in
ordinary CI (docs/MULTIHOST.md "The CI harness").

The old two-process smoke (tests/test_multihost.py pre-ISSUE-14) was
slow-marked and permanently failing: the CPU backend cannot run
cross-process DEVICE collectives, so any test built on a global-mesh
jitted program died with "Multiprocess computations aren't implemented".
What DOES work multi-process on CPU — verified, and what the host path
of the hierarchical composite is built on — is everything on the HOST
plane: the coordination-service KV store and barriers, zmq tile streams,
and per-process LOCAL-mesh SPMD programs. This harness spawns real
``jax.distributed.initialize`` processes (one coordinator, N workers,
each with its own virtual CPU device set) and runs an ENTRY FUNCTION in
every worker, so hierarchical paths, host gathers and the obs-event
merge run for real in CI instead of being skipped.

Usage (from a test)::

    from scenery_insitu_tpu.testing import multiproc

    results = multiproc.run_multiproc(
        "tests.test_multihost:_entry_hier", n_procs=2,
        devices_per_proc=2, workdir=tmp_path)
    assert all(r.returncode == 0 for r in results), results

The entry is ``module:function`` taking one `MPContext`; it runs AFTER
``jax.distributed`` is initialized (through the retry-laddered
``multihost.initialize``) with the CPU backend pinned and the axon TPU
shim popped. Workers share ``workdir`` for artifacts; the parent only
collects exit codes + stdout — assertions live in the entry (a failed
assert is a nonzero exit) and in the parent over the artifacts.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, NamedTuple, Optional


class MPContext(NamedTuple):
    """What an entry function gets: its place in the fleet plus the
    shared scratch directory."""

    process_id: int
    num_processes: int
    workdir: str
    args: tuple = ()


class ProcResult(NamedTuple):
    process_id: int
    returncode: int
    output: str

    @property
    def ok(self) -> bool:
        return self.returncode == 0


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_multiproc(entry: str, n_procs: int, devices_per_proc: int = 2,
                  workdir: Optional[str] = None, args: tuple = (),
                  timeout_s: float = 420.0) -> List[ProcResult]:
    """Spawn ``n_procs`` real jax.distributed worker processes on this
    machine and run ``entry`` (``module:function``) in each. Returns one
    `ProcResult` per worker; a worker that wedges past ``timeout_s`` is
    killed (its siblings too — they would block on the dead coordinator)
    and reported with returncode -9."""
    from scenery_insitu_tpu.utils.backend import virtual_mesh_env

    coordinator = f"127.0.0.1:{free_port()}"
    workdir = workdir or os.getcwd()
    procs = []
    for pid in range(n_procs):
        base = dict(os.environ)
        # each worker pins its OWN virtual device count — the parent's
        # (e.g. the 8-device test mesh) must not leak through
        base["XLA_FLAGS"] = " ".join(
            f for f in base.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f)
        env = virtual_mesh_env(devices_per_proc, base)
        env["_SITPU_POP_AXON"] = "1"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "scenery_insitu_tpu.testing.multiproc",
             "--entry", entry, "--coordinator", coordinator,
             "--processes", str(n_procs), "--process-id", str(pid),
             "--workdir", str(workdir)] + [str(a) for a in args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=_repo_root()))

    results: List[ProcResult] = []
    for pid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout_s)
            results.append(ProcResult(pid, p.returncode,
                                      out.decode("utf-8", "replace")))
        except subprocess.TimeoutExpired:  # sitpu-lint: disable=SITPU-LEDGER — harness verdict IS the ProcResult(-9); nothing degrades silently
            for q in procs:
                if q.poll() is None:
                    q.kill()
            for q in procs:     # reap: SIGKILL delivery is asynchronous
                try:
                    q.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            out = b""
            try:
                out = p.stdout.read() or b""
            except Exception:
                pass
            results.append(ProcResult(pid, -9, out.decode(
                "utf-8", "replace") + f"\n[harness] worker {pid} timed "
                f"out after {timeout_s:.0f}s and was killed"))
    return results


def _child_main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--entry", required=True,
                    help="module:function taking one MPContext")
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--workdir", default=".")
    args, extra = ap.parse_known_args(argv)

    from scenery_insitu_tpu.utils.backend import pin_cpu_backend

    if os.environ.get("_SITPU_POP_AXON") == "1":
        pin_cpu_backend()

    from scenery_insitu_tpu.parallel import multihost

    multihost.initialize(args.coordinator, args.processes,
                         args.process_id, timeout_s=120.0,
                         attempt_timeout_s=30.0)

    import importlib

    mod_name, _, fn_name = args.entry.partition(":")
    if not fn_name:
        raise SystemExit(f"--entry must be module:function, "
                         f"got {args.entry!r}")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    ctx = MPContext(process_id=args.process_id,
                    num_processes=args.processes,
                    workdir=args.workdir, args=tuple(extra))
    rc = 0
    try:
        fn(ctx)
        print(f"[mp {args.process_id}] ENTRY_OK", flush=True)
    except BaseException as e:          # noqa: B036  # sitpu-lint: disable=SITPU-LEDGER — exit code IS the verdict; the parent raises on it
        import traceback

        traceback.print_exc()
        print(f"[mp {args.process_id}] ENTRY_FAILED "
              f"{type(e).__name__}: {e}", flush=True)
        rc = 1
    finally:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    return rc


if __name__ == "__main__":
    sys.exit(_child_main())
