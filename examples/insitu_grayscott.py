"""Standalone in-situ loop: built-in Gray-Scott sim -> distributed VDI
pipeline -> PNG frames (+ optional ZMQ VDI stream and checkpoints).

The counterpart of the reference's DistributedVolumes app
(DistributedVolumes.kt:683-933) — but runnable standalone, which the
reference explicitly could not (its README: "can not be used standalone").

    python examples/insitu_grayscott.py --frames 20 --out out/ --grid 64
    python examples/insitu_grayscott.py --publish tcp://*:6655   # + stream
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--frames", type=int, default=20)
    ap.add_argument("--grid", type=int, default=64)
    ap.add_argument("--ranks", type=int, default=0, help="0 = all devices")
    ap.add_argument("--out", default="out")
    ap.add_argument("--orbit", type=float, default=0.03,
                    help="camera radians/frame")
    ap.add_argument("--publish", default="",
                    help="ZMQ bind address to stream VDIs (e.g. tcp://*:6655)")
    ap.add_argument("--steer-bind", default="",
                    help="ZMQ bind address accepting camera steering "
                         "messages (e.g. tcp://*:6656; pair with "
                         "vdi_client.py --steer)")
    ap.add_argument("--movie", default="",
                    help="also write an .mp4 of the run (movie-writer "
                         "sink, ≅ the reference's VideoEncoder file)")
    ap.add_argument("--live-udp", type=int, default=0,
                    help="also stream frames live over UDP on this port "
                         "(≅ the reference's UDP:3337 video stream; view "
                         "with runtime.streaming.VideoReceiver)")
    ap.add_argument("--prewarm", action="store_true",
                    help="precompile every camera-regime step at startup "
                    "(no mid-orbit compile stalls; see "
                    "InSituSession.prewarm_regimes)")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--resume", default="", help="checkpoint to resume from")
    ap.add_argument("--cpu", action="store_true",
                    help="force an 8-device virtual CPU mesh")
    args = ap.parse_args()

    if args.cpu and os.environ.get("_EX_CHILD") != "1":
        from scenery_insitu_tpu.utils.backend import reexec_virtual_mesh
        reexec_virtual_mesh(8, "_EX_CHILD")
    if os.environ.get("_EX_CHILD") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend
        pin_cpu_backend()

    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.checkpoint import (checkpoint_sink,
                                                       load_session)
    from scenery_insitu_tpu.runtime.session import InSituSession, png_sink

    g = args.grid
    # the flagship mxu engine renders on its intermediate grid (sized by
    # the volume), so this stays fast on any backend; the gather engine
    # at the default 1280x720x512-step render is CPU-prohibitive
    cfg = FrameworkConfig().with_overrides(
        f"sim.grid=[{g},{g},{g}]", f"mesh.num_devices={args.ranks}",
        "slicer.engine=mxu", "vdi.adaptive_mode=temporal",
        "runtime.dataset=gray_scott")
    sinks = [png_sink(args.out)]
    movie = None
    if args.publish:
        from scenery_insitu_tpu.runtime.streaming import (VDIPublisher,
                                                          stream_sink)
        sinks.append(stream_sink(VDIPublisher(args.publish)))
    if args.movie:
        from scenery_insitu_tpu.runtime.streaming import video_sink
        movie = video_sink(args.movie)
        sinks.append(movie)
    if args.live_udp:
        from scenery_insitu_tpu.runtime.streaming import (VideoStreamer,
                                                          live_video_sink)
        sinks.append(live_video_sink(VideoStreamer(port=args.live_udp)))
    sess = InSituSession(cfg, sinks=sinks)
    if args.steer_bind:
        from scenery_insitu_tpu.runtime.streaming import SteeringEndpoint
        sess.steering = SteeringEndpoint(args.steer_bind)
    sess.orbit_rate = args.orbit
    if args.checkpoint_every:
        sess.sinks.append(checkpoint_sink(
            args.out, every=args.checkpoint_every).bind(sess))
    if args.resume:
        load_session(sess, args.resume)
        print(f"resumed at frame {sess.frame_index}")
    if args.prewarm:
        times = sess.prewarm_regimes()
        print("prewarmed regimes:", {k: f"{v}s" for k, v in times.items()})
    try:
        sess.run(args.frames)
    finally:
        if movie is not None:   # finalize the mp4 even on interrupt
            movie.release()
    print(f"wrote {args.frames} frames to {args.out}/ "
          f"(engine={sess.engine}, mode={sess.mode})")


if __name__ == "__main__":
    main()
