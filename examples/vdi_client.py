"""Streamed-VDI client: subscribe to a VDI stream, render novel views
locally, steer the producer's camera — the counterpart of the reference's
remote-viewer chain (ZMQ VDI transport + EfficientVDIRaycast novel-view
rendering + camera messages back, VolumeFromFileExample.kt:996-1046).

Pair with examples/insitu_grayscott.py --publish or
examples/volume_from_file.py --publish:

    python examples/vdi_client.py --connect tcp://localhost:6655 \
        --frames 10 --out client_out/

Tile-granular producers (composite.schedule="waves") work transparently:
`VDISubscriber.receive` assembles tile messages into whole frames, so a
mid-stream join waits for the next complete frame instead of mistaking
one column block for the scene (ISSUE 13 fix). For many concurrent
viewers of one stream, use the edge-serving tier instead —
``python -m scenery_insitu_tpu.serve`` (docs/SERVING.md) — which
batches all cameras into one render per frame.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--connect", default="tcp://localhost:6655")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="seconds to wait for each VDI (a cold producer "
                         "may need a minute+ of jax compile first)")
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--out", default="client_out")
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--height", type=int, default=512)
    ap.add_argument("--yaw", type=float, default=0.15,
                    help="novel-view offset (radians) from the stream pose")
    ap.add_argument("--steer", default="",
                    help="ZMQ address of the producer's steering endpoint "
                         "(insitu_grayscott.py --steer-bind; "
                         "volume_from_file.py does not steer)")
    args = ap.parse_args()

    import numpy as np

    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.ops import vdi_novel
    from scenery_insitu_tpu.runtime.streaming import VDISubscriber
    from scenery_insitu_tpu.utils.image import save_png

    os.makedirs(args.out, exist_ok=True)
    sub = VDISubscriber(args.connect)
    steer = None
    if args.steer:
        from scenery_insitu_tpu.runtime.streaming import SteeringPublisher
        steer = SteeringPublisher(args.steer)

    print(f"listening on {args.connect} …", flush=True)
    from scenery_insitu_tpu.runtime.streaming import StreamDrop
    i = 0
    while i < args.frames:
        got = sub.receive(timeout_ms=int(args.timeout * 1000))
        if got is None:
            print(f"no VDI within {args.timeout:.0f} s; is a producer "
                  "publishing?", flush=True)
            sys.exit(2)
        if isinstance(got, StreamDrop):
            # corrupt/stale message refused by the integrity layer
            # (docs/ROBUSTNESS.md) — wait for the next good frame
            # WITHOUT burning one of the --frames budget
            print(f"dropped {got.kind} message: {got.reason}", flush=True)
            continue
        vdi, meta = got
        # rebuild the generating camera's slice geometry from METADATA ONLY
        spec0 = vdi_novel.axis_spec_from_meta(meta)
        axcam0 = vdi_novel.axis_camera_from_meta(meta, spec0)
        cam = Camera.create(tuple(np.linalg.inv(
            np.asarray(meta.view))[:3, 3]), fov_y_deg=50.0,
            near=0.3, far=20.0)
        novel = orbit(cam, args.yaw)
        # any-view: same-regime plane sweep, or cross-regime via the
        # pre-shaded proxy volume — gather-free either way
        img = vdi_novel.render_vdi_any(vdi, axcam0, spec0, novel,
                                       args.width, args.height)
        save_png(os.path.join(args.out, f"novel{i:03d}.png"),
                 np.asarray(img))
        print(f"frame {int(meta.index)}: rendered novel view "
              f"({i + 1}/{args.frames})", flush=True)
        if steer is not None:
            from scenery_insitu_tpu.runtime.streaming import (
                make_camera_message)
            steer.send(make_camera_message(novel))
        i += 1
    sub.close()


if __name__ == "__main__":
    main()
