"""Ambient-occlusion demo (≅ the inactive AO scaffolding in the
reference's ComputeRaycast.comp:147-191, turned into a working TPU-native
feature — see ops/ao.py): renders a procedural volume with and without AO
on both engines and writes the four PNGs side by side.

    python examples/ao_render.py --out out_ao/ [--strength 0.8] [--radius 4]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="out_ao")
    ap.add_argument("--grid", type=int, default=96)
    ap.add_argument("--width", type=int, default=480)
    ap.add_argument("--height", type=int, default=360)
    ap.add_argument("--strength", type=float, default=0.8)
    ap.add_argument("--radius", type=int, default=4)
    ap.add_argument("--steps", type=int, default=192)
    args = ap.parse_args()

    from scenery_insitu_tpu.utils.backend import (enable_compile_cache,
                                                  pin_cpu_backend, probe_tpu)

    if os.environ.get("JAX_PLATFORMS") == "cpu" or probe_tpu() == 0:
        pin_cpu_backend()
    enable_compile_cache()

    import numpy as np

    from scenery_insitu_tpu.config import RenderConfig, SliceMarchConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.ao import shade_volume_ao
    from scenery_insitu_tpu.ops.raycast import raycast
    from scenery_insitu_tpu.utils.image import save_png

    os.makedirs(args.out, exist_ok=True)
    vol = procedural_volume(args.grid, kind="blobs", seed=5)
    tf = for_dataset("procedural")
    cam = Camera.create((0.5, 0.8, 2.6), fov_y_deg=50.0, near=0.3, far=20.0)
    bg = (1.0, 1.0, 1.0, 1.0)
    w, h = args.width, args.height

    cfg = RenderConfig(max_steps=args.steps, background=bg)
    cfg_ao = RenderConfig(max_steps=args.steps, background=bg,
                          ao_strength=args.strength, ao_radius=args.radius)
    save_png(os.path.join(args.out, "gather_plain.png"),
             np.asarray(raycast(vol, tf, cam, w, h, cfg).image))
    save_png(os.path.join(args.out, "gather_ao.png"),
             np.asarray(raycast(vol, tf, cam, w, h, cfg_ao).image))

    spec = slicer.make_spec(cam, vol.data.shape, SliceMarchConfig())
    save_png(os.path.join(args.out, "mxu_plain.png"),
             np.asarray(slicer.raycast_mxu(vol, tf, cam, w, h, spec,
                                           background=bg).image))
    shaded = shade_volume_ao(vol, tf, args.radius, args.strength)
    save_png(os.path.join(args.out, "mxu_ao.png"),
             np.asarray(slicer.raycast_mxu(shaded, None, cam, w, h, spec,
                                           background=bg).image))
    print(f"wrote 4 images to {args.out}/")


if __name__ == "__main__":
    main()
