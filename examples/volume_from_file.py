"""Offline dataset rendering / VDI generation — the counterpart of the
reference's VolumeFromFileExample (VolumeFromFileExample.kt:69-1116):
load a raw volume (or a procedural one), render a view sweep, optionally
generate + store VDIs and publish them over ZMQ.

    python examples/volume_from_file.py --out out/                # procedural
    python examples/volume_from_file.py --dataset Kingsnake \
        --data-dir /data --out out/ --store-vdis
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="procedural",
                    help="named raw dataset (core.volume dims table) or "
                         "'procedural'")
    ap.add_argument("--data-dir", default=".")
    ap.add_argument("--out", default="out")
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--height", type=int, default=512)
    ap.add_argument("--views", type=int, default=5)
    ap.add_argument("--store-vdis", action="store_true")
    ap.add_argument("--publish", default="",
                    help="ZMQ bind address to stream generated VDIs")
    ap.add_argument("--k", type=int, default=16, help="max supersegments")
    args = ap.parse_args()

    import numpy as np

    from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera, orbit
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.core.volume import load_dataset, procedural_volume
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.utils.image import save_png

    if args.dataset == "procedural":
        vol = procedural_volume(96, kind="blobs", seed=1)
    else:
        vol = load_dataset(args.dataset, args.data_dir)
    tf = for_dataset(args.dataset)
    os.makedirs(args.out, exist_ok=True)

    cam0 = Camera.create((0.0, 0.5, 3.0), fov_y_deg=50.0, near=0.3, far=20.0)
    pub = None
    if args.publish:
        from scenery_insitu_tpu.runtime.streaming import VDIPublisher
        pub = VDIPublisher(args.publish)

    for i in range(args.views):
        cam = orbit(cam0, 2.0 * np.pi * i / max(args.views, 1) * 0.25)
        spec = slicer.make_spec(cam, vol.data.shape, SliceMarchConfig())
        out = slicer.raycast_mxu(vol, tf, cam, args.width, args.height, spec)
        save_png(os.path.join(args.out, f"view{i:03d}.png"),
                 np.asarray(out.image))
        if args.store_vdis or pub is not None:
            vdi, meta, _ = slicer.generate_vdi_mxu(
                vol, tf, cam, spec,
                VDIConfig(max_supersegments=args.k, adaptive_iters=4),
                frame_index=i)
            if args.store_vdis:
                from scenery_insitu_tpu.io.vdi_io import save_vdi
                save_vdi(os.path.join(args.out, f"vdi{i:03d}.npz"),
                         vdi, meta)
            if pub is not None:
                pub.publish(vdi, meta)
        print(f"view {i + 1}/{args.views} done")
    print(f"wrote {args.views} views to {args.out}/")


if __name__ == "__main__":
    main()
